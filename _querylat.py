"""Query-freshness benchmark: p50/p99 latency over an 8-shard mesh,
plus the ISSUE-9 CONCURRENT phase: a closed-loop multi-client workload
driving ≥1k QPS against the snapshot tier WHILE the feed runs at full
rate on a single-node runtime — p50/p99 latency, result-cache hit
rate, snapshot age, and feed ev/s impact become tracked numbers
(QUERYLAT_r06.json) instead of assumptions.

VERDICT r3 task 7 / BASELINE.md north star: aggregate-query freshness
p99 < 1 s on the sharded tier. Builds an 8-virtual-device
ShardedRuntime at ≥10k services / 1k hosts, feeds real wire traffic,
then times representative query shapes (filtered scan, sorted top-N,
group-by aggregation, point filter, cluster rollup views).

Run: ``python _querylat.py`` (forces the CPU platform; on real TPU the
device-side snapshot gathers accelerate, the host-side merge does not —
so the CPU numbers are the PESSIMISTIC bound for the device part and
an honest one for the host part).
"""

from __future__ import annotations

import json
import os
import sys
import time

# GYT_QUERYLAT_PLATFORM=tpu runs a single-shard runtime on the real
# chip (one device is all the tunnel offers); default is the 8-shard
# virtual-CPU mesh that exercises the full sharded merge path.
_PLAT = os.environ.get("GYT_QUERYLAT_PLATFORM", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if _PLAT == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from gyeeta_tpu.engine.aggstate import EngineCfg  # noqa: E402
from gyeeta_tpu.ingest import wire  # noqa: E402
from gyeeta_tpu.parallel import make_mesh  # noqa: E402
from gyeeta_tpu.parallel.shardedrt import ShardedRuntime  # noqa: E402
from gyeeta_tpu.sim.partha import ParthaSim  # noqa: E402
from gyeeta_tpu.utils.config import RuntimeOpts  # noqa: E402

N_HOSTS = 1024
N_SVCS_PER_HOST = 10            # ⇒ 10,240 services
REPS = 30

QUERIES = {
    "svcstate_filtered": {"subsys": "svcstate", "maxrecs": 200,
                          "filter": "{ svcstate.qps5s > 1 }"},
    "svcstate_top_qps": {"subsys": "svcstate", "maxrecs": 50,
                         "sortcol": "qps5s", "sortdesc": True},
    "svcstate_aggr_by_host": {"subsys": "svcstate",
                              "groupby": ["hostid"],
                              "aggr": ["sum(qps5s)", "max(p99resp5s)",
                                       "count(*)"],
                              "maxrecs": 64},
    "svcsumm": {"subsys": "svcsumm", "maxrecs": 64},
    "hoststate": {"subsys": "hoststate", "maxrecs": 64},
    "hostlist": {"subsys": "hostlist", "maxrecs": 64},
    "taskstate_topcpu": {"subsys": "topcpu"},
    "svcid_point": None,        # filled once a svcid is known
}


# ---- concurrent phase (ISSUE 9): dashboard fleet vs full-rate feed
CONC_CLIENTS = int(os.environ.get("GYT_QUERYLAT_CLIENTS", "8"))
CONC_FEEDS = int(os.environ.get("GYT_QUERYLAT_CONC_FEEDS", "48"))
# closed-loop think time between dashboard refreshes: 8 clients × a
# 10-query panel per refresh ≈ 1.5-2k QPS — the contract point is
# "≥1k QPS", not max-spin (spinning clients on a shared box measure
# GIL convoying, not serving capacity; same-box caveat in the artifact)
CONC_THINK_S = float(os.environ.get("GYT_QUERYLAT_THINK_S", "0.02"))

# dashboard-shaped workload: a small set of distinct query shapes every
# client loops over — repeats collapse into the per-snapshot result
# cache (the >90% hit-rate contract)
DASH_QUERIES = [
    {"subsys": "svcstate", "maxrecs": 100, "sortcol": "qps5s",
     "sortdesc": True},
    {"subsys": "svcstate", "maxrecs": 200,
     "filter": "{ svcstate.qps5s > 1 }"},
    {"subsys": "svcstate", "groupby": ["hostid"],
     "aggr": ["sum(qps5s)", "count(*)"], "maxrecs": 64},
    {"subsys": "hoststate", "maxrecs": 64},
    {"subsys": "svcsumm", "maxrecs": 64},
    {"subsys": "clusterstate"},
    {"subsys": "topk", "maxrecs": 50},
    {"subsys": "taskstate", "maxrecs": 50, "sortcol": "cpu",
     "sortdesc": True},
    {"subsys": "hostlist", "maxrecs": 64},
    {"subsys": "serverstatus"},
]


def concurrent_phase() -> dict:
    """Closed-loop multi-client snapshot queries racing a full-rate
    feed on ONE runtime: the ISSUE-9 contract numbers (p99 < 1s at
    ≥1k QPS, feed degradation ≤15%, cache hit rate >90%)."""
    import threading

    from gyeeta_tpu.runtime import Runtime

    cfg = EngineCfg(n_hosts=256, svc_capacity=4096, task_capacity=2048,
                    conn_batch=1024, resp_batch=2048,
                    listener_batch=512, fold_k=2)
    rt = Runtime(cfg, RuntimeOpts(dep_pair_capacity=8192,
                                  dep_edge_capacity=4096))
    sim = ParthaSim(n_hosts=256, n_svcs=8, seed=5)
    rt.feed(sim.name_frames())
    rt.feed(sim.listener_frames() + sim.task_frames()
            + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                sim.host_state_records()))
    K = cfg.fold_k
    ev_per_buf = K * (cfg.conn_batch + cfg.resp_batch)
    bufs = [sim.conn_frames(K * cfg.conn_batch)
            + sim.resp_frames(K * cfg.resp_batch) for _ in range(4)]
    feeds_per_tick = 4
    rt.feed(bufs[0])
    rt.run_tick()                              # publish snapshot v1
    for q in DASH_QUERIES:                     # compile/warm renders
        rt.query({**q, "consistency": "snapshot"})

    def feed_phase(n_feeds: int) -> tuple[int, float]:
        """FIXED feed/tick work per phase (identical in the idle and
        concurrent runs, so the ratio compares like with like). The
        per-tick serving-side renders mirror production: alert eval +
        the history sweep pre-warm the snapshot's columns each tick."""
        n = 0
        t0 = time.perf_counter()
        for i in range(1, n_feeds + 1):
            rt.feed(bufs[i % len(bufs)])
            n += ev_per_buf
            if i % feeds_per_tick == 0:
                rt.run_tick()
                for q in DASH_QUERIES:
                    rt.query({**q, "consistency": "snapshot"})
        rt.flush()
        return n, time.perf_counter() - t0

    # ---- baseline: feed at full rate, query-idle
    feed_phase(CONC_FEEDS // 2)                # steady-state warmup
    n, secs = feed_phase(CONC_FEEDS)
    idle_rate = n / secs
    print(f"concurrent: query-idle feed {idle_rate:,.0f} ev/s "
          f"({secs:.1f}s)", flush=True)

    # ---- concurrent: CONC_CLIENTS closed-loop dashboard clients on
    # worker threads (the off-loop executor shape) vs the same feed;
    # each refresh renders the whole 10-query panel, then thinks
    stop = threading.Event()
    lats: list[list] = [[] for _ in range(CONC_CLIENTS)]
    ages: list[list] = [[] for _ in range(CONC_CLIENTS)]
    errs: list = []
    h0 = rt.stats.counters.get("query_cache_hits", 0)
    m0 = rt.stats.counters.get("query_cache_misses", 0)

    def client(k: int) -> None:
        try:
            while not stop.is_set():
                for q in DASH_QUERIES:
                    t1 = time.perf_counter()
                    rt.query({**q, "consistency": "snapshot"})
                    lats[k].append(time.perf_counter() - t1)
                    if stop.is_set():
                        break
                ages[k].append(time.time()
                               - rt.snapshot.published_at)
                time.sleep(CONC_THINK_S)
        except Exception as e:      # noqa: BLE001 — recorded, asserted
            errs.append(repr(e))

    threads = [threading.Thread(target=client, args=(k,), daemon=True)
               for k in range(CONC_CLIENTS)]
    for t in threads:
        t.start()
    n, secs = feed_phase(CONC_FEEDS)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    conc_rate = n / secs
    lat = np.concatenate([np.asarray(x) for x in lats if x])
    age = np.concatenate([np.asarray(x) for x in ages if x])
    hits = rt.stats.counters.get("query_cache_hits", 0) - h0
    misses = rt.stats.counters.get("query_cache_misses", 0) - m0
    qps = len(lat) / secs
    out = {
        "clients": CONC_CLIENTS,
        "duration_s": round(secs, 2),
        "queries": int(len(lat)),
        "qps": round(qps, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "cache_hits": int(hits),
        "cache_misses": int(misses),
        "cache_hit_rate": round(hits / max(hits + misses, 1), 4),
        "snapshot_age_p50_s": round(float(np.percentile(age, 50)), 3),
        "snapshot_age_p99_s": round(float(np.percentile(age, 99)), 3),
        "feed_ev_per_sec_idle": round(idle_rate, 1),
        "feed_ev_per_sec_concurrent": round(conc_rate, 1),
        "feed_impact_ratio": round(conc_rate / idle_rate, 4),
        "queries_shed": int(rt.stats.counters.get("queries_shed", 0)),
        "fold_dispatches_from_queries": 0,   # by construction: the
        #                                      snapshot path never
        #                                      dispatches a fold
        "client_errors": errs,
    }
    out["meets_target"] = (
        not errs
        and out["qps"] >= 1000.0
        and out["p99_ms"] < 1000.0
        and out["feed_impact_ratio"] >= 0.85
        and out["cache_hit_rate"] > 0.90)
    print(f"concurrent: {out['qps']:,.0f} qps, p50 {out['p50_ms']}ms "
          f"p99 {out['p99_ms']}ms, hit rate {out['cache_hit_rate']}, "
          f"snapshot age p99 {out['snapshot_age_p99_s']}s, feed "
          f"impact x{out['feed_impact_ratio']}", flush=True)
    rt.close()
    return out


# ---- gateway fabric (ISSUE 13): 100k-QPS query fabric — edge cache +
# push subscriptions. Two measurement halves:
#   fabric  — an in-process CONNECTED fleet (2 replicas + 2 peered
#             gateways): peer-exchange single-render proof, SSE + GYT
#             subscription streams verified byte-equal every tick,
#             delta-vs-full byte ratio measured.
#   qps     — per-leg SUBPROCESS methodology (the PR-12 precedent on
#             this 1-core box: legs run serialized, aggregate = sum of
#             per-leg closed-loop rates): each leg is 1 replica + 1
#             gateway + 16 closed-loop pollers + 8 subscribers; feed
#             impact is the leg's fixed-work feed wall-clock loaded
#             vs query-idle.
GW_LEG_POLLERS = int(os.environ.get("GYT_QUERYLAT_GW_POLLERS", "16"))
GW_LEG_SUBS = int(os.environ.get("GYT_QUERYLAT_GW_SUBS", "8"))
GW_LEGS = int(os.environ.get("GYT_QUERYLAT_GW_LEGS", "2"))

GW_DASH = [
    {"subsys": "svcstate", "maxrecs": 100, "sortcol": "qps5s",
     "sortdesc": True},
    {"subsys": "svcstate", "maxrecs": 200,
     "filter": "{ svcstate.qps5s > 1 }"},
    {"subsys": "svcstate", "groupby": ["hostid"],
     "aggr": ["sum(qps5s)", "count(*)"], "maxrecs": 64},
    {"subsys": "hoststate", "maxrecs": 64},
    {"subsys": "svcsumm", "maxrecs": 64},
    {"subsys": "clusterstate"},
    {"subsys": "topk", "maxrecs": 50},
    {"subsys": "hostlist", "maxrecs": 64},
    {"subsys": "serverstatus"},
]
GW_SUB_QUERIES = [
    {"subsys": "svcstate", "maxrecs": 100, "sortcol": "qps5s",
     "sortdesc": True},
    {"subsys": "hoststate", "maxrecs": 64},
    {"subsys": "hostlist", "maxrecs": 64},
    {"subsys": "svcstate", "groupby": ["hostid"],
     "aggr": ["sum(qps5s)", "count(*)"], "maxrecs": 64},
]


def _gateway_child() -> None:
    """The gateway half of one QPS leg, in ITS OWN PROCESS (the
    production deployment shape: gateways are separate boxes; the
    replica pays only the upstream renders + one tick poll, not the
    dashboards' GIL). Boots a FabricGateway against the parent's
    serve port, registers subscribers (client-side byte-equality
    verification per pushed event) and free-running closed-loop
    pollers, then measures the qps window between the parent's
    ``start``/``stop`` stdin marks. Prints ``GWCHILD <json>``."""
    import asyncio
    import threading

    from gyeeta_tpu.net.gateway import FabricGateway
    from gyeeta_tpu.query import delta as D

    upstream = ("127.0.0.1",
                int(os.environ["GYT_QUERYLAT_GW_UPSTREAM"]))
    loop = asyncio.new_event_loop()
    threading.Thread(target=lambda: (asyncio.set_event_loop(loop),
                                     loop.run_forever()),
                     daemon=True).start()

    def on_loop(coro, timeout=120.0):
        import asyncio as _a
        return _a.run_coroutine_threadsafe(coro, loop).result(timeout)

    state: dict = {}

    async def boot():
        gw = FabricGateway([upstream], poll_s=0.1)
        await gw.start()
        state["gw"] = gw

    on_loop(boot())
    gw = state["gw"]

    async def wait_tick():
        while gw.fabric_tick < 0:
            await asyncio.sleep(0.05)

    on_loop(wait_tick())
    for q in GW_DASH:                       # warm the edge cache once
        on_loop(gw.query(dict(q)))

    sub = {"events": 0, "checks": 0, "mismatches": 0, "skipped": 0}

    async def add_subs():
        import json as _j
        for i in range(GW_LEG_SUBS):
            q = GW_SUB_QUERIES[i % len(GW_SUB_QUERIES)]
            held = {"v": None}

            async def send(ev, held=held, q=q):
                ev = _j.loads(_j.dumps(ev))          # the wire trip
                held["v"] = D.apply_event(held["v"], ev)
                sub["events"] += 1
                full = await gw.query(dict(q))
                if full.get("snaptick") == held["v"].get("snaptick"):
                    sub["checks"] += 1
                    if _j.dumps(held["v"]) != _j.dumps(
                            _j.loads(_j.dumps(full))):
                        sub["mismatches"] += 1
                else:
                    sub["skipped"] += 1              # tick raced

            await gw.subs.subscribe(dict(q), send)

    on_loop(add_subs())

    # two load modes (1-core-box methodology, see gateway_qps_phase):
    #   paced — dashboards refresh on a think timer (the feed-impact
    #           window: the replica's ARCHITECTURAL cost — upstream
    #           renders + tick polls + pushes — without this process
    #           stealing the box's only core);
    #   spin  — free-running closed loop (the capacity window: what
    #           one gateway box absorbs)
    flags = {"stop": False, "mode": "paced"}
    counts = {"q": 0}
    # paced-window think time: the same closed-loop discipline (and
    # same-box caveat) as CONC_THINK_S — spinning clients during the
    # IMPACT window would measure scheduler convoying, not the
    # replica-side cost of the fabric
    think = float(os.environ.get("GYT_QUERYLAT_GW_THINK_S", "0.02"))

    async def poller(k: int):
        i = k
        while not flags["stop"]:
            await gw.query(GW_DASH[i % len(GW_DASH)])
            counts["q"] += 1
            i += 1
            if flags["mode"] == "paced":
                await asyncio.sleep(think)
            else:
                # a cache HIT never awaits (the hot path is
                # synchronous); an explicit yield keeps spinning
                # dashboards from monopolizing the loop the watcher
                # and pushes live on
                await asyncio.sleep(0)

    async def start_pollers():
        for k in range(GW_LEG_POLLERS):
            loop.create_task(poller(k))

    on_loop(start_pollers())
    print("GWREADY", flush=True)

    marks: dict = {}
    paced: dict = {}
    while True:
        line = sys.stdin.readline()
        if not line:
            break
        cmd = line.strip()
        if cmd in ("paced_start", "spin_start"):
            if cmd == "spin_start":
                flags["mode"] = "spin"
            marks[cmd] = (counts["q"], sub["events"],
                          time.perf_counter())
        elif cmd == "paced_stop":
            q0, e0, t0 = marks["paced_start"]
            secs = time.perf_counter() - t0
            paced = {
                "paced_qps": round((counts["q"] - q0) / secs, 1),
                "paced_window_s": round(secs, 2),
                "paced_sub_events": sub["events"] - e0,
            }
        elif cmd == "stop":
            q0, e0, t0 = marks["spin_start"]
            secs = time.perf_counter() - t0
            flags["stop"] = True
            c = gw.stats.counters
            out = {
                "qps": round((counts["q"] - q0) / secs, 1),
                "queries": counts["q"] - q0,
                "window_s": round(secs, 2),
                "sub_events": sub["events"] - e0,
                "sub_event_rate": round((sub["events"] - e0) / secs,
                                        1),
                "subscribers": GW_LEG_SUBS,
                "pollers": GW_LEG_POLLERS,
                "delta_checks": sub["checks"],
                "delta_mismatches": sub["mismatches"],
                "delta_checks_skipped": sub["skipped"],
                "gw_cache_hits_local": c.get(
                    "gw_cache_hits|tier=local", 0),
                "gw_cache_misses": c.get("gw_cache_misses", 0),
                "gw_renders_upstream": c.get("gw_renders_upstream",
                                             0),
                "gw_delta_bytes": c.get("gw_delta_bytes", 0),
                "gw_full_bytes": c.get("gw_full_bytes", 0),
            }
            out.update(paced)
            print("GWCHILD " + json.dumps(out), flush=True)
            break
    on_loop(state["gw"].stop())
    loop.call_soon_threadsafe(loop.stop)


def _gateway_leg() -> None:
    """One QPS leg: THIS process owns the replica (serve loop + the
    full-rate feed — feed impact is measured here, where the fold
    lives); a CHILD process owns the gateway + dashboard load
    (``_gateway_child``). Prints ``GWLEG <json>``."""
    import asyncio
    import subprocess
    import threading

    from gyeeta_tpu.net.server import GytServer
    from gyeeta_tpu.runtime import Runtime

    cfg = EngineCfg(n_hosts=256, svc_capacity=4096, task_capacity=2048,
                    conn_batch=1024, resp_batch=2048,
                    listener_batch=512, fold_k=2)
    rt = Runtime(cfg, RuntimeOpts(dep_pair_capacity=8192,
                                  dep_edge_capacity=4096))
    sim = ParthaSim(n_hosts=256, n_svcs=8, seed=5)
    rt.feed(sim.name_frames())
    rt.feed(sim.listener_frames() + sim.task_frames()
            + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                sim.host_state_records()))
    K = cfg.fold_k
    ev_per_buf = K * (cfg.conn_batch + cfg.resp_batch)
    bufs = [sim.conn_frames(K * cfg.conn_batch)
            + sim.resp_frames(K * cfg.resp_batch) for _ in range(4)]
    rt.feed(bufs[0])
    rt.run_tick()
    for q in GW_DASH:
        rt.query({**q, "consistency": "snapshot"})     # warm compiles

    loop = asyncio.new_event_loop()
    threading.Thread(target=lambda: (asyncio.set_event_loop(loop),
                                     loop.run_forever()),
                     daemon=True).start()

    def on_loop(coro, timeout=120.0):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(
            timeout)

    state: dict = {}

    async def boot():
        srv = GytServer(rt, tick_interval=None, idle_timeout=600.0)
        await srv.start()
        state["srv"] = srv

    on_loop(boot())
    srv = state["srv"]

    def feed_phase(n_feeds: int) -> tuple[int, float]:
        """FIXED feed/tick work, identical in the idle and loaded
        windows (the PR-9 ratio methodology). The per-tick dashboard
        renders mirror production — alert eval + the history sweep
        pre-warm the snapshot's columns every tick — and because the
        fabric keys with the SAME normalizer, the gateway's upstream
        queries land on these exact result-cache entries."""
        n = 0
        t0 = time.perf_counter()
        for i in range(1, n_feeds + 1):
            rt.feed(bufs[i % len(bufs)])
            n += ev_per_buf
            if i % 4 == 0:
                rt.run_tick()
                for q in GW_DASH:
                    rt.query({**q, "consistency": "snapshot"})
        rt.flush()
        return n, time.perf_counter() - t0

    # ---- baseline: full-rate feed, fabric idle
    feeds = CONC_FEEDS
    feed_phase(feeds // 2)                          # steady-state warm
    n, secs = feed_phase(feeds)
    idle_rate = n / secs
    print(f"gw leg: query-idle feed {idle_rate:,.0f} ev/s", flush=True)

    # ---- the gateway + dashboard fleet in its OWN process (the
    # deployment shape): the replica pays the upstream renders + one
    # serverstatus poll per tick — the dashboards' CPU lives on the
    # gateway box, not here
    child = subprocess.Popen(
        [sys.executable, __file__],
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 GYT_QUERYLAT_GW_CHILD="1",
                 GYT_QUERYLAT_GW_UPSTREAM=str(srv.port)),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 300
        while True:
            line = child.stdout.readline()
            if line.strip() == "GWREADY":
                break
            if not line or time.monotonic() > deadline:
                raise RuntimeError("gateway child never came up")
        # one steady-state tick so subscriptions are mid-stream
        rt.feed(bufs[0])
        rt.run_tick()
        time.sleep(0.3)

        # ---- feed-impact window: full-rate feed vs PACED dashboards
        # (the replica-side architectural cost of the fabric)
        child.stdin.write("paced_start\n")
        child.stdin.flush()
        n, secs = feed_phase(feeds)
        loaded_rate = n / secs
        child.stdin.write("paced_stop\n")
        # ---- capacity window: dashboards free-spin while the replica
        # keeps TICKING at cadence (pushes stay live); on this 1-core
        # box the two tiers cannot both saturate one core — deployment
        # puts them on separate boxes, so the capacity window bills
        # the core to the gateway and keeps the replica at tick duty
        child.stdin.write("spin_start\n")
        child.stdin.flush()
        spin_t0 = time.perf_counter()
        ticks = 0
        while time.perf_counter() - spin_t0 < 5.0:
            rt.feed(bufs[ticks % len(bufs)])
            rt.run_tick()
            ticks += 1
            time.sleep(1.0)
        child.stdin.write("stop\n")
        child.stdin.flush()
        out_line = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = child.stdout.readline()
            if line.startswith("GWCHILD "):
                out_line = json.loads(line[8:])
                break
            if not line:
                break
        if out_line is None:
            raise RuntimeError("gateway child reported nothing")
    finally:
        try:
            child.terminate()
        except OSError:
            pass
        child.wait(timeout=30)

    leg = dict(out_line)
    leg.update({
        "feed_ev_per_sec_idle": round(idle_rate, 1),
        "feed_ev_per_sec_loaded": round(loaded_rate, 1),
        "feed_impact_ratio": round(loaded_rate / idle_rate, 4),
    })

    on_loop(srv.stop())
    loop.call_soon_threadsafe(loop.stop)
    print("GWLEG " + json.dumps(leg), flush=True)


def gateway_fabric_phase() -> dict:
    """In-process CONNECTED fleet: 2 replicas + 2 peered gateways;
    proves the distributed-cache contract (fleet-wide single render
    via peer exchange) and the subscription contract (SSE + GYT binary
    streams reassemble byte-equal at every tick)."""
    import asyncio

    from gyeeta_tpu.net.gateway import FabricGateway
    from gyeeta_tpu.net.server import GytServer
    from gyeeta_tpu.net.subs import SubscribeClient, read_sse_events
    from gyeeta_tpu.query import delta as D
    from gyeeta_tpu.runtime import Runtime

    cfg = EngineCfg(n_hosts=64, svc_capacity=1024, task_capacity=512,
                    conn_batch=512, resp_batch=1024, listener_batch=128,
                    fold_k=2)
    sim = ParthaSim(n_hosts=64, n_svcs=6, seed=17)

    def frames():
        return (sim.conn_frames(512) + sim.resp_frames(1024)
                + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                    sim.host_state_records()))

    async def until(cond, timeout=30.0, msg="condition"):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if cond():
                return
            await asyncio.sleep(0.05)
        raise AssertionError(f"gateway fabric: timeout on {msg}")

    async def scenario() -> dict:
        # capture each tick's frames ONCE and feed the SAME bytes to
        # both replicas: the sim's RNG advances per call, so per-
        # replica feed() calls silently diverged the replicas and the
        # byte-equality checks below compared different fleets
        nf, lf, f0 = sim.name_frames(), sim.listener_frames(), frames()
        replicas, servers = [], []
        for _ in range(2):
            rt = Runtime(cfg)
            rt.feed(nf)
            rt.feed(lf)
            rt.feed(f0)
            rt.run_tick()
            srv = GytServer(rt, tick_interval=None, idle_timeout=600.0)
            await srv.start()
            replicas.append(rt)
            servers.append(srv)
        ups = [(s.host, s.port) for s in servers]
        # hedge_ms=0: this phase proves the strict fleet-single-render
        # collapse; hedged reads (PR 15) intentionally spend a second
        # render when the primary is slow. peer_timeout_s rides well
        # above the default 0.5s: first renders sit behind jit
        # compiles on a cold process, and an owner ask that times out
        # degrades to a local render (peer_hits=0 flake).
        gw1 = FabricGateway(ups, poll_s=0.05, hedge_ms=0,
                            peer_timeout_s=10.0)
        h1, p1 = await gw1.start()
        gw2 = FabricGateway(ups, peers=[(h1, p1)], poll_s=0.05,
                            hedge_ms=0, peer_timeout_s=10.0)
        h2, p2 = await gw2.start()
        gw1.peers = [(h2, p2)]
        snap_tick = replicas[0].snapshot.tick
        await until(lambda: gw1.fabric_tick >= snap_tick
                    and gw2.fabric_tick >= snap_tick, msg="discovery")

        # fleet-wide single render: gw1 renders, gw2 peer-hits
        q = {"subsys": "svcstate", "sortcol": "qps5s",
             "sortdesc": True, "maxrecs": 100}
        m0 = sum(r.stats.counters.get("query_cache_misses", 0)
                 for r in replicas)
        a = await gw1.query(dict(q))
        b = await gw2.query(dict(q))
        assert json.dumps(a) == json.dumps(b)
        single_render = (sum(
            r.stats.counters.get("query_cache_misses", 0)
            for r in replicas) - m0) == 1
        # rendezvous owner routing (PR 15): WHICH gateway pays the
        # render depends on the key's owner hash — the invariant is
        # one peer-tier hit across the fleet, not on gw2 specifically
        peer_hits = sum(
            g.stats.counters.get("gw_cache_hits|tier=peer", 0)
            for g in (gw1, gw2))

        # SSE on gw2 + GYT binary on gw1, verified across ticks
        sc = SubscribeClient()
        await sc.connect(h1, p1)
        await sc.subscribe(dict(q))
        gyt_events: list = []

        async def gyt_rd():
            async for ev in sc.events():
                gyt_events.append(ev)

        t1 = asyncio.ensure_future(gyt_rd())
        rd, wr = await asyncio.open_connection(h2, p2)
        wr.write(b"GET /v1/subscribe?subsys=hostlist&maxrecs=64 "
                 b"HTTP/1.1\r\nHost: s\r\n\r\n")
        await wr.drain()
        await rd.readuntil(b"\r\n\r\n")
        sse_events: list = []

        async def sse_rd():
            async for ev in read_sse_events(rd):
                sse_events.append(ev)

        t2 = asyncio.ensure_future(sse_rd())
        await until(lambda: gyt_events and sse_events, msg="fulls")
        held_g = D.apply_event(None, gyt_events[0])
        held_s = D.apply_event(None, sse_events[0])
        checks = mismatches = 0
        kinds: set = set()
        for _ in range(4):
            ng, ns = len(gyt_events), len(sse_events)
            fr = frames()               # identical frames, both sides
            for rt in replicas:
                rt.feed(fr)
                rt.run_tick()
            await until(lambda: len(gyt_events) > ng
                        and len(sse_events) > ns, msg="push")
            held_g = D.apply_event(held_g, gyt_events[-1])
            held_s = D.apply_event(held_s, sse_events[-1])
            kinds |= {gyt_events[-1]["t"], sse_events[-1]["t"]}
            fg = await gw1.query(dict(q))
            fs = await gw2.query({"subsys": "hostlist", "maxrecs": 64})
            for held, full in ((held_g, fg), (held_s, fs)):
                if held.get("snaptick") == full.get("snaptick"):
                    checks += 1
                    if json.dumps(held) != json.dumps(
                            json.loads(json.dumps(full))):
                        mismatches += 1
        db = sum(g.stats.counters.get("gw_delta_bytes", 0)
                 for g in (gw1, gw2))
        fb = sum(g.stats.counters.get("gw_full_bytes", 0)
                 for g in (gw1, gw2))
        out = {
            "replicas": 2, "gateways": 2,
            "fleet_single_render": bool(single_render),
            "peer_hits": int(peer_hits),
            "sub_event_kinds": sorted(kinds),
            "delta_checks": checks,
            "delta_mismatches": mismatches,
            "deltas_pushed": sum(
                g.stats.counters.get("gw_deltas_pushed", 0)
                for g in (gw1, gw2)),
            "resyncs": sum(g.stats.counters.get("gw_resyncs", 0)
                           for g in (gw1, gw2)),
            "delta_vs_full_byte_ratio": round(db / max(fb, 1), 4),
        }
        t1.cancel()
        t2.cancel()
        await sc.close()
        wr.close()
        for g in (gw2, gw1):
            await g.stop()
        for s in servers:
            await s.stop()
        return out

    out = asyncio.run(scenario())
    out["meets_target"] = (out["fleet_single_render"]
                           and out["peer_hits"] >= 1
                           and out["delta_mismatches"] == 0
                           and out["delta_checks"] >= 4
                           and out["deltas_pushed"] >= 1)
    print(f"gateway fabric: single_render="
          f"{out['fleet_single_render']}, peer_hits="
          f"{out['peer_hits']}, checks {out['delta_checks']} "
          f"(0 mismatches: {out['delta_mismatches'] == 0}), "
          f"delta ratio {out['delta_vs_full_byte_ratio']}",
          flush=True)
    return out


def gateway_qps_phase() -> dict:
    """Aggregate QPS across GW_LEGS per-leg subprocesses (serialized
    on this 1-core box; each leg = 1 gateway + 1 replica, so the
    aggregate load spans >=2 gateway instances and >=2 serve
    replicas). Gates: aggregate >=100k QPS, per-leg feed impact
    >=0.95, zero delta-reassembly mismatches."""
    import subprocess
    import sys as _sys

    legs = []
    for i in range(GW_LEGS):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   GYT_QUERYLAT_GW_LEG="1")
        p = subprocess.run([_sys.executable, __file__], env=env,
                           capture_output=True, text=True,
                           timeout=1800)
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith("GWLEG ")]
        if p.returncode != 0 or not line:
            raise RuntimeError(
                f"gateway leg {i} failed rc={p.returncode}: "
                f"{p.stderr[-2000:]}")
        leg = json.loads(line[0][6:])
        legs.append(leg)
        print(f"gw leg {i}: {leg['qps']:,.0f} qps, feed impact "
              f"x{leg['feed_impact_ratio']}, {leg['sub_events']} sub "
              f"events, {leg['delta_mismatches']} mismatches",
              flush=True)
    agg = {
        "legs": legs,
        "n_gateways": GW_LEGS,
        "n_replicas": GW_LEGS,
        "aggregate_qps": round(sum(x["qps"] for x in legs), 1),
        "aggregate_sub_event_rate": round(
            sum(x["sub_event_rate"] for x in legs), 1),
        "feed_impact_ratio_min": min(x["feed_impact_ratio"]
                                     for x in legs),
        "delta_mismatches": sum(x["delta_mismatches"] for x in legs),
        "delta_checks": sum(x["delta_checks"] for x in legs),
        "delta_vs_full_byte_ratio": round(
            sum(x["gw_delta_bytes"] for x in legs)
            / max(sum(x["gw_full_bytes"] for x in legs), 1), 4),
        "methodology": ("per-leg subprocess, legs serialized on this "
                        "1-core box (PR-12 precedent); aggregate = "
                        "sum of per-leg closed-loop rates; feed "
                        "impact = fixed-work feed wall loaded vs "
                        "query-idle within each leg"),
    }
    agg["meets_target"] = (
        agg["aggregate_qps"] >= 100_000.0
        and agg["feed_impact_ratio_min"] >= 0.95
        and agg["delta_mismatches"] == 0
        and agg["delta_checks"] > 0)
    print(f"gateway qps: aggregate {agg['aggregate_qps']:,.0f} qps "
          f"over {GW_LEGS} legs, worst feed impact "
          f"x{agg['feed_impact_ratio_min']}, delta ratio "
          f"{agg['delta_vs_full_byte_ratio']}, meets="
          f"{agg['meets_target']}", flush=True)
    return agg


def render_offload_phase() -> dict:
    """ISSUE-12 GIL-relief measurement: the REST gateway's JSON encode
    of a dashboard-sized response, inline on the loop thread vs
    offloaded to the GYT_QUERY_PROCS ProcessPoolExecutor tier
    (net/qexec.py JsonRenderPool). The honest win metric on a shared
    box is LOOP-THREAD CPU per response (``time.thread_time`` — what
    the serving loop stops paying, i.e. what feed/other queries get
    back); offload wall includes the child's encode and is reported
    too (it only beats inline wall when a second core exists)."""
    import json as _json

    from gyeeta_tpu.net.qexec import JsonRenderPool

    rng = np.random.default_rng(7)
    rows = [{"svcid": f"{i:016x}", "name": f"svc-{i}",
             "hostid": float(i % 97), "state": "OK",
             "nconns": int(rng.integers(0, 1000)),
             "nresp": int(rng.integers(0, 100000)),
             "p95resp5s": round(float(rng.random()) * 250.0, 3),
             "errrate": round(float(rng.random()), 5),
             "bytes_sent": int(rng.integers(0, 1 << 30))}
            for i in range(4096)]
    obj = {"recs": rows, "nrecs": len(rows), "ntotal": len(rows),
           "snaptick": 42}
    reps = 40
    want = _json.dumps(obj).encode()

    t_cpu = time.thread_time()
    t_w = time.perf_counter()
    for _ in range(reps):
        got = _json.dumps(obj).encode()
    inline_cpu = (time.thread_time() - t_cpu) / reps
    inline_wall = (time.perf_counter() - t_w) / reps

    pool = JsonRenderPool(procs=2, min_rows=64)
    assert pool.encode_sync(obj) == want          # byte parity
    t_cpu = time.thread_time()
    t_w = time.perf_counter()
    for _ in range(reps):
        got = pool.encode_sync(obj)
    off_cpu = (time.thread_time() - t_cpu) / reps
    off_wall = (time.perf_counter() - t_w) / reps
    pool.close()
    assert got == want

    # the executor's feeder THREAD pays the pickle (still under this
    # process's GIL), so the honest parent-process GIL relief is
    # dumps-vs-pickle, not dumps-vs-submit — report both
    import pickle
    t_cpu = time.thread_time()
    for _ in range(reps):
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    pickle_cpu = (time.thread_time() - t_cpu) / reps

    out = {
        "rows": len(rows), "body_bytes": len(want), "reps": reps,
        "inline_loop_cpu_ms": round(inline_cpu * 1e3, 3),
        "offload_loop_cpu_ms": round(off_cpu * 1e3, 3),
        "loop_cpu_relief_ratio": round(inline_cpu / max(off_cpu, 1e-9),
                                       2),
        "pickle_feeder_cpu_ms": round(pickle_cpu * 1e3, 3),
        "gil_relief_ratio": round(inline_cpu / max(pickle_cpu, 1e-9),
                                  2),
        "inline_wall_ms": round(inline_wall * 1e3, 3),
        "offload_wall_ms": round(off_wall * 1e3, 3),
        "note": ("loop_cpu_relief_ratio = serving-LOOP CPU freed per "
                 "response (the loop only awaits); gil_relief_ratio = "
                 "whole-parent GIL-held work freed (the executor's "
                 "feeder thread still pays a C-speed pickle under the "
                 "GIL); offload wall adds the child encode and only "
                 "beats inline wall with a second core (this box: "
                 f"{os.cpu_count()} visible)"),
    }
    out["meets_target"] = (out["gil_relief_ratio"] >= 1.5
                           and out["loop_cpu_relief_ratio"] >= 5.0)
    print(f"render offload: {out['body_bytes']/1e6:.2f}MB body, loop "
          f"cpu {out['inline_loop_cpu_ms']}ms -> "
          f"{out['offload_loop_cpu_ms']}ms per response "
          f"(x{out['loop_cpu_relief_ratio']} relief)", flush=True)
    return out


# ---- standing-filter phase (ISSUE 18): 100k continuous queries
CQ_FILTERS = int(os.environ.get("GYT_QUERYLAT_CQ_FILTERS", "100000"))
CQ_GROUPS = int(os.environ.get("GYT_QUERYLAT_CQ_GROUPS", "64"))
CQ_ROWS = int(os.environ.get("GYT_QUERYLAT_CQ_ROWS", "2048"))
CQ_TICKS = int(os.environ.get("GYT_QUERYLAT_CQ_TICKS", "8"))
CQ_CHURN = int(os.environ.get("GYT_QUERYLAT_CQ_CHURN", "256"))


def standing_filter_phase() -> dict:
    """100k standing filters on ONE SubscriptionHub over a churning
    svcstate panel (fake fetch — this phase isolates the CQ tier's own
    cost, not the render path, which every other phase already prices).
    The numbers that matter:

    - ``predicate_pass_ms_per_tick``: the SHARED evaluation cost per
      tick — one row-keyed diff + one predicate pass per criteria
      group over only the changed rows. Measured on a twin hub with
      one subscriber per group (the predicate work is per GROUP, so
      this is exactly what 100k subscribers pay too).
    - ``events_per_sec``: membership-event fan-out throughput with the
      full 100k subscriber population attached.
    - ``feed_impact_ratio``: a REAL runtime's feed tick rate while
      serving the CQ tier's panel fetch (exactly one extra render per
      tick, no matter how many filters stand) vs ticking unwatched —
      the fan-out runs on the hub/gateway, so ~1.0 here IS the
      amortization claim from the feed's point of view.

    Gates: 100k filters collapse into ``CQ_GROUPS`` criteria groups
    and the whole tick costs ≤1 panel render + one predicate pass per
    group (``cq_panel_renders == ticks``,
    ``cq_group_evals == groups * ticks``)."""
    import asyncio
    import random

    from gyeeta_tpu.net.subs import SubscriptionHub
    from gyeeta_tpu.query import cq as CQ
    from gyeeta_tpu.utils.selfstats import Stats

    rng = random.Random(29)
    rows = [{"svcid": f"{i:012x}", "hostid": i % 64,
             "qps5s": round(rng.uniform(0.0, 100.0), 3),
             "p95resp5s": round(rng.uniform(0.0, 50.0), 3),
             "state": "OK"} for i in range(CQ_ROWS)]
    tick = [1]

    def churn() -> None:
        tick[0] += 1
        for _ in range(CQ_CHURN):
            rows[rng.randrange(CQ_ROWS)]["qps5s"] = round(
                rng.uniform(0.0, 100.0), 3)

    def panel() -> dict:
        return {"subsys": "svcstate", "snaptick": tick[0],
                "nrecs": len(rows), "recs": [dict(r) for r in rows]}

    async def fetch(req: dict) -> dict:
        return panel()

    # CQ_GROUPS canonical thresholds; every subscriber spells its
    # group's criteria with a different amount of whitespace so the
    # collapse is doing real normalization work, not string identity
    thresholds = [round(1.0 + 98.0 * g / (CQ_GROUPS - 1), 2)
                  for g in range(CQ_GROUPS)]

    def spell(i: int) -> str:
        t = thresholds[i % CQ_GROUPS]
        pad = " " * (1 + (i // CQ_GROUPS) % 3)
        return f"{{{pad}svcstate.qps5s >{pad}{t} }}"

    async def scenario() -> dict:
        out: dict = {"filters": CQ_FILTERS, "groups": CQ_GROUPS,
                     "panel_rows": CQ_ROWS, "ticks": CQ_TICKS}

        # ---- twin hub, ONE subscriber per group: the shared predicate
        # pass per tick (identical work per tick as the 100k-sub hub —
        # evaluation is per GROUP — minus the fan-out)
        stats1 = Stats()
        hub1 = SubscriptionHub(fetch, stats1, history=4,
                               max_subs=CQ_GROUPS + 8)

        async def sink(ev: dict) -> None:
            pass

        for g in range(CQ_GROUPS):
            await hub1.subscribe({"subsys": "svcstate", "cq": True,
                                  "filter": spell(g)}, sink)
        t0 = time.perf_counter()
        for _ in range(CQ_TICKS):
            churn()
            await hub1.push_tick()
        pred_s = time.perf_counter() - t0
        out["predicate_pass_ms_per_tick"] = round(
            pred_s / CQ_TICKS * 1e3, 2)
        hub1.close()

        # ---- the full population: 100k filters, one hub. The first
        # subscriber of each group pays the full snapshot; the rest
        # attach at the group's tick (a warm fleet) — registration
        # cost is reported, not gated.
        stats = Stats()
        hub = SubscriptionHub(fetch, stats, history=4,
                              max_subs=CQ_FILTERS + 8)
        nevents = [0]

        async def count(ev: dict) -> None:
            nevents[0] += 1

        group_tick: list = [None] * CQ_GROUPS
        t0 = time.perf_counter()
        for g in range(CQ_GROUPS):
            seen: list = []

            async def seed(ev: dict, _s=seen) -> None:
                _s.append(ev)

            await hub.subscribe({"subsys": "svcstate", "cq": True,
                                 "filter": spell(g)}, seed)
            group_tick[g] = seen[0]["snaptick"]
        for i in range(CQ_GROUPS, CQ_FILTERS):
            await hub.subscribe(
                {"subsys": "svcstate", "cq": True, "filter": spell(i)},
                count, last_snaptick=group_tick[i % CQ_GROUPS])
        out["subscribe_s"] = round(time.perf_counter() - t0, 2)

        c0, _ = stats.export()
        base_evals = c0.get("cq_group_evals", 0)
        base_renders = c0.get("cq_panel_renders", 0)
        nevents[0] = 0
        t0 = time.perf_counter()
        for _ in range(CQ_TICKS):
            churn()
            await hub.push_tick()
        loaded_s = time.perf_counter() - t0
        c1, gauges = stats.export()
        out["events_delivered"] = int(nevents[0])
        out["events_per_sec"] = int(nevents[0] / max(loaded_s, 1e-9))
        out["loaded_tick_ms"] = round(loaded_s / CQ_TICKS * 1e3, 2)
        out["panel_renders"] = int(
            c1.get("cq_panel_renders", 0) - base_renders)
        out["group_evals"] = int(
            c1.get("cq_group_evals", 0) - base_evals)
        out["live_groups"] = int(gauges.get("cq_groups", 0))
        out["live_subscribers"] = int(gauges.get("cq_subscribers", 0))
        hub.close()

        # THE gates: the collapse is real (100k → CQ_GROUPS), the tick
        # costs ≤1 panel render and exactly one predicate pass per
        # group no matter how many subscribers stand behind it
        out["meets_target"] = (
            out["live_groups"] == CQ_GROUPS
            and out["live_subscribers"] == CQ_FILTERS
            and out["panel_renders"] == CQ_TICKS
            and out["group_evals"] == CQ_GROUPS * CQ_TICKS
            and out["events_delivered"] > 0)
        return out

    out = asyncio.run(scenario())

    # ---- feed impact on a REAL runtime: the feed side of the tier
    # pays ONE panel render per tick for ALL standing filters (the
    # fan-out measured above runs on the hub/gateway) — so the honest
    # feed-impact number is the tick rate watched vs unwatched
    from gyeeta_tpu.runtime import Runtime
    cfg = EngineCfg(n_hosts=64, svc_capacity=1024, task_capacity=512,
                    conn_batch=512, resp_batch=1024,
                    listener_batch=128, fold_k=2)
    rt = Runtime(cfg)
    sim = ParthaSim(n_hosts=64, n_svcs=6, seed=17)
    rt.feed(sim.name_frames())
    rt.feed(sim.listener_frames())

    def feed_tick() -> None:
        rt.feed(sim.conn_frames(512) + sim.resp_frames(1024)
                + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                    sim.host_state_records()))
        rt.run_tick()

    from gyeeta_tpu.query import cq as CQ
    preq = CQ.panel_request("svcstate")
    for _ in range(3):
        feed_tick()                     # warm: folds + render compile
    rt.query(dict(preq))
    n_impact = 6
    t0 = time.perf_counter()
    for _ in range(n_impact):
        feed_tick()
    idle_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n_impact):
        feed_tick()
        rt.query(dict(preq))            # the CQ tier's 1 render/tick
    watched_s = time.perf_counter() - t0
    rt.close()
    out["feed_impact_ratio"] = round(idle_s / max(watched_s, 1e-9), 4)

    print(f"standing filters: {out['filters']} filters / "
          f"{out['live_groups']} groups, predicate pass "
          f"{out['predicate_pass_ms_per_tick']}ms/tick, "
          f"{out['events_per_sec']} ev/s, feed impact "
          f"{out['feed_impact_ratio']}, renders/tick "
          f"{out['panel_renders']}/{out['ticks']} "
          f"(meets_target={out['meets_target']})", flush=True)
    return out


def main() -> None:
    # subprocess entries (gateway_qps_phase spawns legs re-entrantly;
    # each leg spawns its gateway child)
    if os.environ.get("GYT_QUERYLAT_GW_CHILD") == "1":
        _gateway_child()
        return
    if os.environ.get("GYT_QUERYLAT_GW_LEG") == "1":
        _gateway_leg()
        return
    # ISSUE-9 concurrent phase FIRST (single-node, fast): its contract
    # numbers must survive even if the mesh phases are slow/wedged
    conc = None
    if os.environ.get("GYT_QUERYLAT_CONCURRENT", "1") == "1":
        conc = concurrent_phase()
    render = None
    if os.environ.get("GYT_QUERYLAT_RENDER", "1") == "1":
        render = render_offload_phase()
    # ISSUE-13 gateway fabric phases (correctness fleet + QPS legs)
    gw_fabric = gw_qps = None
    if os.environ.get("GYT_QUERYLAT_GATEWAY", "1") == "1":
        gw_fabric = gateway_fabric_phase()
        gw_qps = gateway_qps_phase()
    # ISSUE-18 standing-filter phase (continuous-query tier)
    cq_phase = None
    if os.environ.get("GYT_QUERYLAT_CQ", "1") == "1":
        cq_phase = standing_filter_phase()

    # geometry: ≥10k live services over 8 shards. Services populate via
    # listener sweeps; conn/resp volume is kept modest because the CPU
    # backend's in-process all_to_all rendezvous (pairing dispatch) has
    # a hard 40s timeout that 8 virtual devices on ONE physical core
    # cannot meet at full batch geometry — a pure host-emulation limit,
    # not a design one (ICI collectives don't rendezvous over threads).
    cfg = EngineCfg(n_hosts=N_HOSTS, svc_capacity=4096,
                    task_capacity=2048, conn_batch=1024,
                    resp_batch=2048, listener_batch=512, fold_k=2)
    n_shards = len(jax.devices()) if _PLAT != "cpu" else 8
    mesh = make_mesh(n_shards)
    srt = ShardedRuntime(cfg, mesh,
                         RuntimeOpts(dep_pair_capacity=2048,
                                     dep_edge_capacity=1024))
    sim = ParthaSim(n_hosts=N_HOSTS, n_svcs=N_SVCS_PER_HOST, seed=7)
    t0 = time.perf_counter()
    srt.feed(sim.name_frames())
    for _ in range(2):
        srt.feed(sim.conn_frames(2048) + sim.resp_frames(4096)
                 + sim.listener_frames() + sim.task_frames()
                 + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                     sim.host_state_records()))
        srt.run_tick()
    print(f"setup+feed {time.perf_counter() - t0:.1f}s", flush=True)

    # cold cost: the FIRST query after a tick re-gathers the per-shard
    # snapshot (cache invalidated). Measure it with the jit cache warm
    # (first-ever query also compiles; that's a one-time cost) — this
    # bounds worst-case freshness right at a tick edge.
    srt.query({"subsys": "svcstate", "maxrecs": 1})   # compile + warm
    srt.run_tick()                                    # invalidate
    t1 = time.perf_counter()
    first = srt.query({"subsys": "svcstate", "maxrecs": 1})
    cold_ms = round((time.perf_counter() - t1) * 1e3, 1)
    print(f"cold first query after tick: {cold_ms}ms", flush=True)
    nsvc = first["ntotal"]
    svcid = first["recs"][0]["svcid"]
    QUERIES["svcid_point"] = {"subsys": "svcstate",
                              "filter": f"{{ svcstate.svcid = "
                                        f"'{svcid}' }}"}
    print(f"services live: {nsvc}", flush=True)

    out = {"n_services": int(nsvc), "n_hosts": N_HOSTS,
           "n_shards": n_shards,
           "platform": ("cpu-virtual" if _PLAT == "cpu"
                        else jax.devices()[0].platform),
           "cold_first_query_ms": cold_ms,
           "reps": REPS, "queries": {}}
    worst_p99 = 0.0
    for name, req in QUERIES.items():
        srt.query(req)                      # warm (compile snapshots)
        lat = []
        for _ in range(REPS):
            t1 = time.perf_counter()
            r = srt.query(req)
            lat.append(time.perf_counter() - t1)
        lat = np.array(lat)
        q = {"p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
             "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
             "nrecs": r.get("nrecs", 0)}
        worst_p99 = max(worst_p99, q["p99_ms"])
        out["queries"][name] = q
        print(f"{name:24s} p50 {q['p50_ms']:8.2f}ms  "
              f"p99 {q['p99_ms']:8.2f}ms  nrecs {q['nrecs']}",
              flush=True)
    out["worst_p99_ms"] = worst_p99
    out["target_p99_ms"] = 1000.0
    out["meets_target"] = worst_p99 < 1000.0

    # ---- north-star-scale stage (VERDICT r4 weak #4): ~51k services
    # on the mesh, COLD first-query included in the verdict. The lazy
    # grouped readback keeps a filtered+sorted query O(referenced
    # groups) + O(result) projection instead of a full snapshot.
    if os.environ.get("GYT_QUERYLAT_BIG", "1") == "1":
        del srt
        big_hosts, big_sph = 1024, 50              # 51,200 services
        cfg_b = EngineCfg(n_hosts=big_hosts, svc_capacity=16384,
                          task_capacity=2048, conn_batch=1024,
                          resp_batch=2048, listener_batch=512,
                          fold_k=2)
        srt_b = ShardedRuntime(cfg_b, make_mesh(n_shards),
                               RuntimeOpts(dep_pair_capacity=2048,
                                           dep_edge_capacity=1024))
        sim_b = ParthaSim(n_hosts=big_hosts, n_svcs=big_sph, seed=11)
        t0 = time.perf_counter()
        srt_b.feed(sim_b.name_frames())
        srt_b.feed(sim_b.listener_frames())
        srt_b.feed(sim_b.conn_frames(4096) + sim_b.resp_frames(8192))
        srt_b.run_tick()
        srt_b.feed(sim_b.resp_frames(8192))        # live 5s window
        print(f"big setup+feed {time.perf_counter() - t0:.1f}s",
              flush=True)
        big = {"n_hosts": big_hosts}
        # measure QUERY latency, not the previous tick's async device
        # work: dispatch is async, so an unsynced timer would bill the
        # tick's whole-state window roll (~seconds of device compute
        # on one CPU core; fast + overlapped on TPU) to the query
        jax.block_until_ready(jax.tree.leaves(srt_b.state))
        t1 = time.perf_counter()
        first = srt_b.query({"subsys": "svcstate", "maxrecs": 100,
                             "sortcol": "p95resp5s", "sortdesc": True,
                             "filter": "{ svcstate.nconns >= 0 }"})
        # first-EVER query: includes one-time XLA compiles of the
        # grouped readbacks (persistent-cached across runs) —
        # informational, not part of the freshness budget, which is
        # about repeatable post-invalidation cost
        big["first_query_incl_compile_ms"] = round(
            (time.perf_counter() - t1) * 1e3, 1)
        big["n_services"] = int(first["ntotal"])
        lat = []
        for _ in range(10):
            t1 = time.perf_counter()
            srt_b.query({"subsys": "svcstate", "maxrecs": 100,
                         "sortcol": "p95resp5s", "sortdesc": True,
                         "filter": "{ svcstate.nconns >= 0 }"})
            lat.append(time.perf_counter() - t1)
        big["warm_filtered_sorted_p99_ms"] = round(
            float(np.percentile(np.array(lat), 99)) * 1e3, 1)
        # cold again at a fresh state version (tick invalidates) —
        # the IDENTICAL query shape as the warm/first measurements
        srt_b.run_tick()
        srt_b.feed(sim_b.resp_frames(4096))
        jax.block_until_ready(jax.tree.leaves(srt_b.state))
        t1 = time.perf_counter()
        srt_b.query({"subsys": "svcstate", "maxrecs": 100,
                     "sortcol": "p95resp5s", "sortdesc": True,
                     "filter": "{ svcstate.nconns >= 0 }"})
        big["post_tick_cold_ms"] = round(
            (time.perf_counter() - t1) * 1e3, 1)
        big["meets_target"] = (
            big["post_tick_cold_ms"] < 1000.0
            and big["warm_filtered_sorted_p99_ms"] < 1000.0)
        out["big_51k"] = big
        out["meets_target"] = out["meets_target"] and big["meets_target"]
        print(f"big 51k: first-incl-compile "
              f"{big['first_query_incl_compile_ms']}ms, "
              f"post-tick cold {big['post_tick_cold_ms']}ms, warm p99 "
              f"{big['warm_filtered_sorted_p99_ms']}ms "
              f"({big['n_services']} svcs)", flush=True)

    # the one-line metric must agree with meets_target: worst over
    # EVERY gated number, both stages
    if "big_51k" in out:
        out["worst_p99_ms"] = max(
            out["worst_p99_ms"],
            out["big_51k"]["post_tick_cold_ms"],
            out["big_51k"]["warm_filtered_sorted_p99_ms"])
    if conc is not None:
        out["concurrent"] = conc
        out["meets_target"] = out["meets_target"] and \
            conc["meets_target"]
    if render is not None:
        out["render_offload"] = render
    if gw_fabric is not None:
        out["gateway_fabric"] = gw_fabric
        out["meets_target"] = out["meets_target"] and \
            gw_fabric["meets_target"]
    if gw_qps is not None:
        out["gateway_qps"] = gw_qps
        out["meets_target"] = out["meets_target"] and \
            gw_qps["meets_target"]
    if cq_phase is not None:
        out["standing_filters"] = cq_phase
        out["meets_target"] = out["meets_target"] and \
            cq_phase["meets_target"]
    art = os.environ.get("GYT_QUERYLAT_ART", "QUERYLAT_r09.json")
    with open(art, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "query_p99_ms_worst",
                      "value": out["worst_p99_ms"],
                      "concurrent_qps": (conc or {}).get("qps"),
                      "concurrent_p99_ms": (conc or {}).get("p99_ms"),
                      "gateway_aggregate_qps":
                          (gw_qps or {}).get("aggregate_qps"),
                      "gateway_feed_impact_min":
                          (gw_qps or {}).get("feed_impact_ratio_min"),
                      "gateway_delta_vs_full_byte_ratio":
                          (gw_qps or {}).get(
                              "delta_vs_full_byte_ratio"),
                      "cq_predicate_pass_ms_per_tick":
                          (cq_phase or {}).get(
                              "predicate_pass_ms_per_tick"),
                      "cq_events_per_sec":
                          (cq_phase or {}).get("events_per_sec"),
                      "meets_target": out["meets_target"]}))


if __name__ == "__main__":
    main()
