"""Query-freshness benchmark: p50/p99 latency over an 8-shard mesh,
plus the ISSUE-9 CONCURRENT phase: a closed-loop multi-client workload
driving ≥1k QPS against the snapshot tier WHILE the feed runs at full
rate on a single-node runtime — p50/p99 latency, result-cache hit
rate, snapshot age, and feed ev/s impact become tracked numbers
(QUERYLAT_r06.json) instead of assumptions.

VERDICT r3 task 7 / BASELINE.md north star: aggregate-query freshness
p99 < 1 s on the sharded tier. Builds an 8-virtual-device
ShardedRuntime at ≥10k services / 1k hosts, feeds real wire traffic,
then times representative query shapes (filtered scan, sorted top-N,
group-by aggregation, point filter, cluster rollup views).

Run: ``python _querylat.py`` (forces the CPU platform; on real TPU the
device-side snapshot gathers accelerate, the host-side merge does not —
so the CPU numbers are the PESSIMISTIC bound for the device part and
an honest one for the host part).
"""

from __future__ import annotations

import json
import os
import time

# GYT_QUERYLAT_PLATFORM=tpu runs a single-shard runtime on the real
# chip (one device is all the tunnel offers); default is the 8-shard
# virtual-CPU mesh that exercises the full sharded merge path.
_PLAT = os.environ.get("GYT_QUERYLAT_PLATFORM", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if _PLAT == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from gyeeta_tpu.engine.aggstate import EngineCfg  # noqa: E402
from gyeeta_tpu.ingest import wire  # noqa: E402
from gyeeta_tpu.parallel import make_mesh  # noqa: E402
from gyeeta_tpu.parallel.shardedrt import ShardedRuntime  # noqa: E402
from gyeeta_tpu.sim.partha import ParthaSim  # noqa: E402
from gyeeta_tpu.utils.config import RuntimeOpts  # noqa: E402

N_HOSTS = 1024
N_SVCS_PER_HOST = 10            # ⇒ 10,240 services
REPS = 30

QUERIES = {
    "svcstate_filtered": {"subsys": "svcstate", "maxrecs": 200,
                          "filter": "{ svcstate.qps5s > 1 }"},
    "svcstate_top_qps": {"subsys": "svcstate", "maxrecs": 50,
                         "sortcol": "qps5s", "sortdesc": True},
    "svcstate_aggr_by_host": {"subsys": "svcstate",
                              "groupby": ["hostid"],
                              "aggr": ["sum(qps5s)", "max(p99resp5s)",
                                       "count(*)"],
                              "maxrecs": 64},
    "svcsumm": {"subsys": "svcsumm", "maxrecs": 64},
    "hoststate": {"subsys": "hoststate", "maxrecs": 64},
    "hostlist": {"subsys": "hostlist", "maxrecs": 64},
    "taskstate_topcpu": {"subsys": "topcpu"},
    "svcid_point": None,        # filled once a svcid is known
}


# ---- concurrent phase (ISSUE 9): dashboard fleet vs full-rate feed
CONC_CLIENTS = int(os.environ.get("GYT_QUERYLAT_CLIENTS", "8"))
CONC_FEEDS = int(os.environ.get("GYT_QUERYLAT_CONC_FEEDS", "48"))
# closed-loop think time between dashboard refreshes: 8 clients × a
# 10-query panel per refresh ≈ 1.5-2k QPS — the contract point is
# "≥1k QPS", not max-spin (spinning clients on a shared box measure
# GIL convoying, not serving capacity; same-box caveat in the artifact)
CONC_THINK_S = float(os.environ.get("GYT_QUERYLAT_THINK_S", "0.02"))

# dashboard-shaped workload: a small set of distinct query shapes every
# client loops over — repeats collapse into the per-snapshot result
# cache (the >90% hit-rate contract)
DASH_QUERIES = [
    {"subsys": "svcstate", "maxrecs": 100, "sortcol": "qps5s",
     "sortdesc": True},
    {"subsys": "svcstate", "maxrecs": 200,
     "filter": "{ svcstate.qps5s > 1 }"},
    {"subsys": "svcstate", "groupby": ["hostid"],
     "aggr": ["sum(qps5s)", "count(*)"], "maxrecs": 64},
    {"subsys": "hoststate", "maxrecs": 64},
    {"subsys": "svcsumm", "maxrecs": 64},
    {"subsys": "clusterstate"},
    {"subsys": "topk", "maxrecs": 50},
    {"subsys": "taskstate", "maxrecs": 50, "sortcol": "cpu",
     "sortdesc": True},
    {"subsys": "hostlist", "maxrecs": 64},
    {"subsys": "serverstatus"},
]


def concurrent_phase() -> dict:
    """Closed-loop multi-client snapshot queries racing a full-rate
    feed on ONE runtime: the ISSUE-9 contract numbers (p99 < 1s at
    ≥1k QPS, feed degradation ≤15%, cache hit rate >90%)."""
    import threading

    from gyeeta_tpu.runtime import Runtime

    cfg = EngineCfg(n_hosts=256, svc_capacity=4096, task_capacity=2048,
                    conn_batch=1024, resp_batch=2048,
                    listener_batch=512, fold_k=2)
    rt = Runtime(cfg, RuntimeOpts(dep_pair_capacity=8192,
                                  dep_edge_capacity=4096))
    sim = ParthaSim(n_hosts=256, n_svcs=8, seed=5)
    rt.feed(sim.name_frames())
    rt.feed(sim.listener_frames() + sim.task_frames()
            + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                sim.host_state_records()))
    K = cfg.fold_k
    ev_per_buf = K * (cfg.conn_batch + cfg.resp_batch)
    bufs = [sim.conn_frames(K * cfg.conn_batch)
            + sim.resp_frames(K * cfg.resp_batch) for _ in range(4)]
    feeds_per_tick = 4
    rt.feed(bufs[0])
    rt.run_tick()                              # publish snapshot v1
    for q in DASH_QUERIES:                     # compile/warm renders
        rt.query({**q, "consistency": "snapshot"})

    def feed_phase(n_feeds: int) -> tuple[int, float]:
        """FIXED feed/tick work per phase (identical in the idle and
        concurrent runs, so the ratio compares like with like). The
        per-tick serving-side renders mirror production: alert eval +
        the history sweep pre-warm the snapshot's columns each tick."""
        n = 0
        t0 = time.perf_counter()
        for i in range(1, n_feeds + 1):
            rt.feed(bufs[i % len(bufs)])
            n += ev_per_buf
            if i % feeds_per_tick == 0:
                rt.run_tick()
                for q in DASH_QUERIES:
                    rt.query({**q, "consistency": "snapshot"})
        rt.flush()
        return n, time.perf_counter() - t0

    # ---- baseline: feed at full rate, query-idle
    feed_phase(CONC_FEEDS // 2)                # steady-state warmup
    n, secs = feed_phase(CONC_FEEDS)
    idle_rate = n / secs
    print(f"concurrent: query-idle feed {idle_rate:,.0f} ev/s "
          f"({secs:.1f}s)", flush=True)

    # ---- concurrent: CONC_CLIENTS closed-loop dashboard clients on
    # worker threads (the off-loop executor shape) vs the same feed;
    # each refresh renders the whole 10-query panel, then thinks
    stop = threading.Event()
    lats: list[list] = [[] for _ in range(CONC_CLIENTS)]
    ages: list[list] = [[] for _ in range(CONC_CLIENTS)]
    errs: list = []
    h0 = rt.stats.counters.get("query_cache_hits", 0)
    m0 = rt.stats.counters.get("query_cache_misses", 0)

    def client(k: int) -> None:
        try:
            while not stop.is_set():
                for q in DASH_QUERIES:
                    t1 = time.perf_counter()
                    rt.query({**q, "consistency": "snapshot"})
                    lats[k].append(time.perf_counter() - t1)
                    if stop.is_set():
                        break
                ages[k].append(time.time()
                               - rt.snapshot.published_at)
                time.sleep(CONC_THINK_S)
        except Exception as e:      # noqa: BLE001 — recorded, asserted
            errs.append(repr(e))

    threads = [threading.Thread(target=client, args=(k,), daemon=True)
               for k in range(CONC_CLIENTS)]
    for t in threads:
        t.start()
    n, secs = feed_phase(CONC_FEEDS)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    conc_rate = n / secs
    lat = np.concatenate([np.asarray(x) for x in lats if x])
    age = np.concatenate([np.asarray(x) for x in ages if x])
    hits = rt.stats.counters.get("query_cache_hits", 0) - h0
    misses = rt.stats.counters.get("query_cache_misses", 0) - m0
    qps = len(lat) / secs
    out = {
        "clients": CONC_CLIENTS,
        "duration_s": round(secs, 2),
        "queries": int(len(lat)),
        "qps": round(qps, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "cache_hits": int(hits),
        "cache_misses": int(misses),
        "cache_hit_rate": round(hits / max(hits + misses, 1), 4),
        "snapshot_age_p50_s": round(float(np.percentile(age, 50)), 3),
        "snapshot_age_p99_s": round(float(np.percentile(age, 99)), 3),
        "feed_ev_per_sec_idle": round(idle_rate, 1),
        "feed_ev_per_sec_concurrent": round(conc_rate, 1),
        "feed_impact_ratio": round(conc_rate / idle_rate, 4),
        "queries_shed": int(rt.stats.counters.get("queries_shed", 0)),
        "fold_dispatches_from_queries": 0,   # by construction: the
        #                                      snapshot path never
        #                                      dispatches a fold
        "client_errors": errs,
    }
    out["meets_target"] = (
        not errs
        and out["qps"] >= 1000.0
        and out["p99_ms"] < 1000.0
        and out["feed_impact_ratio"] >= 0.85
        and out["cache_hit_rate"] > 0.90)
    print(f"concurrent: {out['qps']:,.0f} qps, p50 {out['p50_ms']}ms "
          f"p99 {out['p99_ms']}ms, hit rate {out['cache_hit_rate']}, "
          f"snapshot age p99 {out['snapshot_age_p99_s']}s, feed "
          f"impact x{out['feed_impact_ratio']}", flush=True)
    rt.close()
    return out


def render_offload_phase() -> dict:
    """ISSUE-12 GIL-relief measurement: the REST gateway's JSON encode
    of a dashboard-sized response, inline on the loop thread vs
    offloaded to the GYT_QUERY_PROCS ProcessPoolExecutor tier
    (net/qexec.py JsonRenderPool). The honest win metric on a shared
    box is LOOP-THREAD CPU per response (``time.thread_time`` — what
    the serving loop stops paying, i.e. what feed/other queries get
    back); offload wall includes the child's encode and is reported
    too (it only beats inline wall when a second core exists)."""
    import json as _json

    from gyeeta_tpu.net.qexec import JsonRenderPool

    rng = np.random.default_rng(7)
    rows = [{"svcid": f"{i:016x}", "name": f"svc-{i}",
             "hostid": float(i % 97), "state": "OK",
             "nconns": int(rng.integers(0, 1000)),
             "nresp": int(rng.integers(0, 100000)),
             "p95resp5s": round(float(rng.random()) * 250.0, 3),
             "errrate": round(float(rng.random()), 5),
             "bytes_sent": int(rng.integers(0, 1 << 30))}
            for i in range(4096)]
    obj = {"recs": rows, "nrecs": len(rows), "ntotal": len(rows),
           "snaptick": 42}
    reps = 40
    want = _json.dumps(obj).encode()

    t_cpu = time.thread_time()
    t_w = time.perf_counter()
    for _ in range(reps):
        got = _json.dumps(obj).encode()
    inline_cpu = (time.thread_time() - t_cpu) / reps
    inline_wall = (time.perf_counter() - t_w) / reps

    pool = JsonRenderPool(procs=2, min_rows=64)
    assert pool.encode_sync(obj) == want          # byte parity
    t_cpu = time.thread_time()
    t_w = time.perf_counter()
    for _ in range(reps):
        got = pool.encode_sync(obj)
    off_cpu = (time.thread_time() - t_cpu) / reps
    off_wall = (time.perf_counter() - t_w) / reps
    pool.close()
    assert got == want

    # the executor's feeder THREAD pays the pickle (still under this
    # process's GIL), so the honest parent-process GIL relief is
    # dumps-vs-pickle, not dumps-vs-submit — report both
    import pickle
    t_cpu = time.thread_time()
    for _ in range(reps):
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    pickle_cpu = (time.thread_time() - t_cpu) / reps

    out = {
        "rows": len(rows), "body_bytes": len(want), "reps": reps,
        "inline_loop_cpu_ms": round(inline_cpu * 1e3, 3),
        "offload_loop_cpu_ms": round(off_cpu * 1e3, 3),
        "loop_cpu_relief_ratio": round(inline_cpu / max(off_cpu, 1e-9),
                                       2),
        "pickle_feeder_cpu_ms": round(pickle_cpu * 1e3, 3),
        "gil_relief_ratio": round(inline_cpu / max(pickle_cpu, 1e-9),
                                  2),
        "inline_wall_ms": round(inline_wall * 1e3, 3),
        "offload_wall_ms": round(off_wall * 1e3, 3),
        "note": ("loop_cpu_relief_ratio = serving-LOOP CPU freed per "
                 "response (the loop only awaits); gil_relief_ratio = "
                 "whole-parent GIL-held work freed (the executor's "
                 "feeder thread still pays a C-speed pickle under the "
                 "GIL); offload wall adds the child encode and only "
                 "beats inline wall with a second core (this box: "
                 f"{os.cpu_count()} visible)"),
    }
    out["meets_target"] = (out["gil_relief_ratio"] >= 1.5
                           and out["loop_cpu_relief_ratio"] >= 5.0)
    print(f"render offload: {out['body_bytes']/1e6:.2f}MB body, loop "
          f"cpu {out['inline_loop_cpu_ms']}ms -> "
          f"{out['offload_loop_cpu_ms']}ms per response "
          f"(x{out['loop_cpu_relief_ratio']} relief)", flush=True)
    return out


def main() -> None:
    # ISSUE-9 concurrent phase FIRST (single-node, fast): its contract
    # numbers must survive even if the mesh phases are slow/wedged
    conc = None
    if os.environ.get("GYT_QUERYLAT_CONCURRENT", "1") == "1":
        conc = concurrent_phase()
    render = None
    if os.environ.get("GYT_QUERYLAT_RENDER", "1") == "1":
        render = render_offload_phase()

    # geometry: ≥10k live services over 8 shards. Services populate via
    # listener sweeps; conn/resp volume is kept modest because the CPU
    # backend's in-process all_to_all rendezvous (pairing dispatch) has
    # a hard 40s timeout that 8 virtual devices on ONE physical core
    # cannot meet at full batch geometry — a pure host-emulation limit,
    # not a design one (ICI collectives don't rendezvous over threads).
    cfg = EngineCfg(n_hosts=N_HOSTS, svc_capacity=4096,
                    task_capacity=2048, conn_batch=1024,
                    resp_batch=2048, listener_batch=512, fold_k=2)
    n_shards = len(jax.devices()) if _PLAT != "cpu" else 8
    mesh = make_mesh(n_shards)
    srt = ShardedRuntime(cfg, mesh,
                         RuntimeOpts(dep_pair_capacity=2048,
                                     dep_edge_capacity=1024))
    sim = ParthaSim(n_hosts=N_HOSTS, n_svcs=N_SVCS_PER_HOST, seed=7)
    t0 = time.perf_counter()
    srt.feed(sim.name_frames())
    for _ in range(2):
        srt.feed(sim.conn_frames(2048) + sim.resp_frames(4096)
                 + sim.listener_frames() + sim.task_frames()
                 + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                     sim.host_state_records()))
        srt.run_tick()
    print(f"setup+feed {time.perf_counter() - t0:.1f}s", flush=True)

    # cold cost: the FIRST query after a tick re-gathers the per-shard
    # snapshot (cache invalidated). Measure it with the jit cache warm
    # (first-ever query also compiles; that's a one-time cost) — this
    # bounds worst-case freshness right at a tick edge.
    srt.query({"subsys": "svcstate", "maxrecs": 1})   # compile + warm
    srt.run_tick()                                    # invalidate
    t1 = time.perf_counter()
    first = srt.query({"subsys": "svcstate", "maxrecs": 1})
    cold_ms = round((time.perf_counter() - t1) * 1e3, 1)
    print(f"cold first query after tick: {cold_ms}ms", flush=True)
    nsvc = first["ntotal"]
    svcid = first["recs"][0]["svcid"]
    QUERIES["svcid_point"] = {"subsys": "svcstate",
                              "filter": f"{{ svcstate.svcid = "
                                        f"'{svcid}' }}"}
    print(f"services live: {nsvc}", flush=True)

    out = {"n_services": int(nsvc), "n_hosts": N_HOSTS,
           "n_shards": n_shards,
           "platform": ("cpu-virtual" if _PLAT == "cpu"
                        else jax.devices()[0].platform),
           "cold_first_query_ms": cold_ms,
           "reps": REPS, "queries": {}}
    worst_p99 = 0.0
    for name, req in QUERIES.items():
        srt.query(req)                      # warm (compile snapshots)
        lat = []
        for _ in range(REPS):
            t1 = time.perf_counter()
            r = srt.query(req)
            lat.append(time.perf_counter() - t1)
        lat = np.array(lat)
        q = {"p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
             "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
             "nrecs": r.get("nrecs", 0)}
        worst_p99 = max(worst_p99, q["p99_ms"])
        out["queries"][name] = q
        print(f"{name:24s} p50 {q['p50_ms']:8.2f}ms  "
              f"p99 {q['p99_ms']:8.2f}ms  nrecs {q['nrecs']}",
              flush=True)
    out["worst_p99_ms"] = worst_p99
    out["target_p99_ms"] = 1000.0
    out["meets_target"] = worst_p99 < 1000.0

    # ---- north-star-scale stage (VERDICT r4 weak #4): ~51k services
    # on the mesh, COLD first-query included in the verdict. The lazy
    # grouped readback keeps a filtered+sorted query O(referenced
    # groups) + O(result) projection instead of a full snapshot.
    if os.environ.get("GYT_QUERYLAT_BIG", "1") == "1":
        del srt
        big_hosts, big_sph = 1024, 50              # 51,200 services
        cfg_b = EngineCfg(n_hosts=big_hosts, svc_capacity=16384,
                          task_capacity=2048, conn_batch=1024,
                          resp_batch=2048, listener_batch=512,
                          fold_k=2)
        srt_b = ShardedRuntime(cfg_b, make_mesh(n_shards),
                               RuntimeOpts(dep_pair_capacity=2048,
                                           dep_edge_capacity=1024))
        sim_b = ParthaSim(n_hosts=big_hosts, n_svcs=big_sph, seed=11)
        t0 = time.perf_counter()
        srt_b.feed(sim_b.name_frames())
        srt_b.feed(sim_b.listener_frames())
        srt_b.feed(sim_b.conn_frames(4096) + sim_b.resp_frames(8192))
        srt_b.run_tick()
        srt_b.feed(sim_b.resp_frames(8192))        # live 5s window
        print(f"big setup+feed {time.perf_counter() - t0:.1f}s",
              flush=True)
        big = {"n_hosts": big_hosts}
        # measure QUERY latency, not the previous tick's async device
        # work: dispatch is async, so an unsynced timer would bill the
        # tick's whole-state window roll (~seconds of device compute
        # on one CPU core; fast + overlapped on TPU) to the query
        jax.block_until_ready(jax.tree.leaves(srt_b.state))
        t1 = time.perf_counter()
        first = srt_b.query({"subsys": "svcstate", "maxrecs": 100,
                             "sortcol": "p95resp5s", "sortdesc": True,
                             "filter": "{ svcstate.nconns >= 0 }"})
        # first-EVER query: includes one-time XLA compiles of the
        # grouped readbacks (persistent-cached across runs) —
        # informational, not part of the freshness budget, which is
        # about repeatable post-invalidation cost
        big["first_query_incl_compile_ms"] = round(
            (time.perf_counter() - t1) * 1e3, 1)
        big["n_services"] = int(first["ntotal"])
        lat = []
        for _ in range(10):
            t1 = time.perf_counter()
            srt_b.query({"subsys": "svcstate", "maxrecs": 100,
                         "sortcol": "p95resp5s", "sortdesc": True,
                         "filter": "{ svcstate.nconns >= 0 }"})
            lat.append(time.perf_counter() - t1)
        big["warm_filtered_sorted_p99_ms"] = round(
            float(np.percentile(np.array(lat), 99)) * 1e3, 1)
        # cold again at a fresh state version (tick invalidates) —
        # the IDENTICAL query shape as the warm/first measurements
        srt_b.run_tick()
        srt_b.feed(sim_b.resp_frames(4096))
        jax.block_until_ready(jax.tree.leaves(srt_b.state))
        t1 = time.perf_counter()
        srt_b.query({"subsys": "svcstate", "maxrecs": 100,
                     "sortcol": "p95resp5s", "sortdesc": True,
                     "filter": "{ svcstate.nconns >= 0 }"})
        big["post_tick_cold_ms"] = round(
            (time.perf_counter() - t1) * 1e3, 1)
        big["meets_target"] = (
            big["post_tick_cold_ms"] < 1000.0
            and big["warm_filtered_sorted_p99_ms"] < 1000.0)
        out["big_51k"] = big
        out["meets_target"] = out["meets_target"] and big["meets_target"]
        print(f"big 51k: first-incl-compile "
              f"{big['first_query_incl_compile_ms']}ms, "
              f"post-tick cold {big['post_tick_cold_ms']}ms, warm p99 "
              f"{big['warm_filtered_sorted_p99_ms']}ms "
              f"({big['n_services']} svcs)", flush=True)

    # the one-line metric must agree with meets_target: worst over
    # EVERY gated number, both stages
    if "big_51k" in out:
        out["worst_p99_ms"] = max(
            out["worst_p99_ms"],
            out["big_51k"]["post_tick_cold_ms"],
            out["big_51k"]["warm_filtered_sorted_p99_ms"])
    if conc is not None:
        out["concurrent"] = conc
        out["meets_target"] = out["meets_target"] and \
            conc["meets_target"]
    if render is not None:
        out["render_offload"] = render
    art = os.environ.get("GYT_QUERYLAT_ART", "QUERYLAT_r07.json")
    with open(art, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "query_p99_ms_worst",
                      "value": out["worst_p99_ms"],
                      "concurrent_qps": (conc or {}).get("qps"),
                      "concurrent_p99_ms": (conc or {}).get("p99_ms"),
                      "meets_target": out["meets_target"]}))


if __name__ == "__main__":
    main()
