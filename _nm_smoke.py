"""CI smoke: boot a server, open a STOCK node-webserver (NM) conn via
sim/nodeweb.py, run one QUERY_WEB_JSON and one CRUD_ALERT_JSON
create→list→delete round trip — fail loud on any wire or routing
breakage.

The protocol-compatibility contract a stock Gyeeta NodeJS webserver
depends on, checked end-to-end with zero external deps and zero
GYT-specific frames on the NM conn. Exit code 0 = contract holds.
Run by ci.sh; standalone: ``JAX_PLATFORMS=cpu python _nm_smoke.py``.
"""

from __future__ import annotations

import asyncio
import sys


async def scenario() -> None:
    from gyeeta_tpu.engine.aggstate import EngineCfg
    from gyeeta_tpu.net import GytServer, NetAgent
    from gyeeta_tpu.runtime import Runtime
    from gyeeta_tpu.sim.nodeweb import NodeWebSim

    cfg = EngineCfg(n_hosts=4, svc_capacity=64, conn_batch=64,
                    resp_batch=64, fold_k=2)
    rt = Runtime(cfg)
    srv = GytServer(rt, tick_interval=None)
    host, port = await srv.start()
    agent = NetAgent(seed=1)
    await agent.connect(host, port)
    await agent.send_sweep(n_conn=128, n_resp=128)
    await asyncio.sleep(0.05)
    rt.run_tick()

    nw = NodeWebSim(hostname="ci-nodeweb")
    hs = await nw.connect(host, port)
    assert hs["error_code"] == 0, hs
    assert hs["madhava_name"] == "gyt-tpu", hs

    # one web query: the agent's sweep must be visible over NM
    out = await nw.query_web("svcstate", maxrecs=100)
    assert out["nrecs"] > 0, f"no svcstate rows over NM: {out}"

    # one alertdef CRUD round trip: create → list shows it → delete →
    # list no longer shows it
    name = "ci-nm-smoke-def"
    add = await nw.crud_alert({
        "op": "add", "objtype": "alertdef", "alertname": name,
        "subsys": "svcstate", "filter": "{ svcstate.state in 'Severe' }"})
    assert add.get("ok") is True, add
    lst = await nw.query_web("alertdef")
    assert any(r.get("alertname") == name for r in lst["recs"]), lst
    dele = await nw.crud_alert({"op": "delete", "objtype": "alertdef",
                                "name": name})
    assert dele.get("ok") is True, dele
    lst2 = await nw.query_web("alertdef")
    assert not any(r.get("alertname") == name for r in lst2["recs"]), lst2

    # the edge's own counters made it into the exposition
    met = await nw.query_web("metrics")
    assert 'gyt_nm_queries_total{verb="web_json"}' in met["text"]
    assert 'gyt_nm_queries_total{verb="crud_alert_json"}' in met["text"]

    await nw.close()
    await agent.close()
    await srv.stop()
    print(f"nm smoke: OK — handshake + svcstate query "
          f"({out['nrecs']} rows) + alertdef CRUD round trip",
          file=sys.stderr)


def main() -> int:
    asyncio.run(scenario())
    return 0


if __name__ == "__main__":
    sys.exit(main())
