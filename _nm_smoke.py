"""CI smoke: boot a server, open a STOCK node-webserver (NM) conn via
sim/nodeweb.py, run one QUERY_WEB_JSON and one CRUD_ALERT_JSON
create→list→delete round trip — fail loud on any wire or routing
breakage.

The protocol-compatibility contract a stock Gyeeta NodeJS webserver
depends on, checked end-to-end with zero external deps and zero
GYT-specific frames on the NM conn. Exit code 0 = contract holds.
Run by ci.sh; standalone: ``JAX_PLATFORMS=cpu python _nm_smoke.py``.
"""

from __future__ import annotations

import asyncio
import sys


async def _rest_query(gh, gp, req: dict) -> tuple:
    """POST /query against the web gateway → (raw body, parsed)."""
    import json

    reader, writer = await asyncio.open_connection(gh, gp)
    body = json.dumps(req).encode()
    writer.write(
        b"POST /query HTTP/1.1\r\nHost: s\r\nConnection: close\r\n"
        + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    raw = await reader.read(-1)
    writer.close()
    head, _, rbody = raw.partition(b"\r\n\r\n")
    assert b" 200 " in head.splitlines()[0], head
    return rbody, json.loads(rbody)


async def scenario() -> None:
    import json

    from gyeeta_tpu.engine.aggstate import EngineCfg
    from gyeeta_tpu.net import GytServer, NetAgent
    from gyeeta_tpu.net.webgw import WebGateway
    from gyeeta_tpu.runtime import Runtime
    from gyeeta_tpu.sim.nodeweb import NodeWebSim

    cfg = EngineCfg(n_hosts=4, svc_capacity=64, conn_batch=64,
                    resp_batch=64, fold_k=2)
    rt = Runtime(cfg)
    srv = GytServer(rt, tick_interval=None)
    host, port = await srv.start()
    agent = NetAgent(seed=1)
    await agent.connect(host, port)
    await agent.send_sweep(n_conn=128, n_resp=128)
    await asyncio.sleep(0.05)
    rt.run_tick()

    nw = NodeWebSim(hostname="ci-nodeweb")
    hs = await nw.connect(host, port)
    assert hs["error_code"] == 0, hs
    assert hs["madhava_name"] == "gyt-tpu", hs

    # one web query: the agent's sweep must be visible over NM
    out = await nw.query_web("svcstate", maxrecs=100)
    assert out["nrecs"] > 0, f"no svcstate rows over NM: {out}"

    # heavy-hitter subsystem on BOTH query edges against the live
    # serve (ISSUE 7): non-empty, every row bound-annotated, and the
    # NM and REST renderings byte-equal
    gw = WebGateway(host, port)
    gh, gp = await gw.start()
    topk_req = {"subsys": "topk", "maxrecs": 50}
    nm_topk = await nw.query_web("topk", maxrecs=50)
    rest_raw, rest_topk = await _rest_query(gh, gp, topk_req)
    assert nm_topk["nrecs"] > 0, f"no topk rows over NM: {nm_topk}"
    assert all("errbound" in r and "source" in r
               for r in nm_topk["recs"]), "topk rows not bound-annotated"
    assert json.dumps(nm_topk).encode() == rest_raw, \
        "topk NM vs REST bytes differ"
    await gw.stop()

    # one alertdef CRUD round trip: create → list shows it → delete →
    # list no longer shows it
    name = "ci-nm-smoke-def"
    add = await nw.crud_alert({
        "op": "add", "objtype": "alertdef", "alertname": name,
        "subsys": "svcstate", "filter": "{ svcstate.state in 'Severe' }"})
    assert add.get("ok") is True, add
    lst = await nw.query_web("alertdef")
    assert any(r.get("alertname") == name for r in lst["recs"]), lst
    dele = await nw.crud_alert({"op": "delete", "objtype": "alertdef",
                                "name": name})
    assert dele.get("ok") is True, dele
    lst2 = await nw.query_web("alertdef")
    assert not any(r.get("alertname") == name for r in lst2["recs"]), lst2

    # the edge's own counters made it into the exposition
    met = await nw.query_web("metrics")
    assert 'gyt_nm_queries_total{verb="web_json"}' in met["text"]
    assert 'gyt_nm_queries_total{verb="crud_alert_json"}' in met["text"]

    await nw.close()
    await agent.close()
    await srv.stop()
    print(f"nm smoke: OK — handshake + svcstate query "
          f"({out['nrecs']} rows) + topk NM/REST parity "
          f"({nm_topk['nrecs']} bound-annotated rows) "
          f"+ alertdef CRUD round trip",
          file=sys.stderr)


def main() -> int:
    asyncio.run(scenario())
    return 0


if __name__ == "__main__":
    sys.exit(main())
