#!/bin/sh
# CI entry: test suite on the 8-device virtual CPU platform.
# (tests/conftest.py forces JAX_PLATFORMS=cpu + the device count itself.)
#
#   ./ci.sh            full suite (slow: ~15 min on a 1-core box)
#   ./ci.sh fast       unit tier only (-m "not slow", a few minutes) —
#                      run this on every change; the full suite at least
#                      once before shipping
set -e
cd "$(dirname "$0")"

# Build the native ingest extension from source — never trust a
# checked-in libgytdeframe.so (a stale binary would silently fall back
# or, worse, pass tests the current deframe.cpp wouldn't). A broken
# compile fails CI loudly; a host without a C++ toolchain skips with a
# reason and the suite runs on the pure-Python decode path.
if command -v g++ >/dev/null 2>&1; then
    rm -f gyeeta_tpu/ingest/native/libgytdeframe.so
    if ! python -m gyeeta_tpu.ingest.native.build; then
        echo "ci: FATAL — native ingest extension failed to compile" >&2
        exit 1
    fi
else
    echo "ci: SKIP native build (no C++ toolchain on this host);" \
         "tests run on the pure-Python decode path" >&2
fi

# ABI compile probe: prove the stock-struct transcriptions against the
# host C++ compiler's layout (offsetof/sizeof for every adapted struct).
# Skips itself with a reason when no toolchain; any drift fails CI.
echo "ci: ABI compile probe" >&2
if ! JAX_PLATFORMS=cpu python -m gyeeta_tpu.ingest.native.abiprobe; then
    echo "ci: FATAL — ABI probe found layout drift" >&2
    exit 1
fi

# /metrics exposition smoke: boot server + gateway, scrape, validate
# the Prometheus text contract with the built-in minimal parser (no
# external deps). Catches a broken scraper surface before the suite.
echo "ci: /metrics exposition smoke" >&2
if ! JAX_PLATFORMS=cpu python _metrics_smoke.py; then
    echo "ci: FATAL — /metrics smoke failed" >&2
    exit 1
fi

# NM query-edge smoke: boot a server, open a STOCK node-webserver conn
# (sim/nodeweb.py — zero GYT frames on the wire), run one
# QUERY_WEB_JSON and one CRUD_ALERT_JSON create→list→delete round trip,
# and query the `topk` heavy-hitter subsystem over BOTH the NM conn and
# the REST gateway — non-empty, bound-annotated, byte-equal renderings.
echo "ci: NM query-edge smoke" >&2
if ! JAX_PLATFORMS=cpu python _nm_smoke.py; then
    echo "ci: FATAL — NM smoke failed" >&2
    exit 1
fi

# History / time-travel smoke: feed → seal WAL → compact into columnar
# snapshot shards (retention downsample demonstrated) → RESTART a fresh
# runtime over the shard dir → query svcstate?at= + topk?window= over
# REST and a stock NM conn, asserting non-empty bound-annotated rows
# rendered byte-equal on both edges.
echo "ci: history time-travel smoke" >&2
if ! JAX_PLATFORMS=cpu python _hist_smoke.py; then
    echo "ci: FATAL — history smoke failed" >&2
    exit 1
fi

# Snapshot-serving QPS smoke: boot a TICKING server + REST gateway,
# feed from a NetAgent while 8 concurrent clients hammer svcstate/
# topk/hoststate — asserts non-empty single-tick-consistent rows,
# nonzero result-cache hits, and zero sheds at smoke load.
echo "ci: snapshot query-serving QPS smoke" >&2
if ! JAX_PLATFORMS=cpu python _qps_smoke.py; then
    echo "ci: FATAL — QPS smoke failed" >&2
    exit 1
fi

# Edge pre-aggregation smoke: a GYT_PREAGG=1 server negotiates delta
# mode with a default agent while an opted-out agent feeds raw sweeps;
# svcstate/hoststate agree byte-equal on REST and stock NM, the delta
# host's counters match the agent's own exact partials, and
# gyt_preagg_* counters render in /metrics.
echo "ci: edge pre-aggregation smoke" >&2
if ! JAX_PLATFORMS=cpu python _preagg_smoke.py; then
    echo "ci: FATAL — preagg smoke failed" >&2
    exit 1
fi

# Query-fabric gateway smoke: 2 serve replicas + 1 gateway — a query
# rendered once upstream serves every later client from the shared
# (snaptick, request-hash) edge cache (replica render counters prove
# the single render), an SSE subscriber receives a pushed event after
# a fed tick that reassembles byte-equal to a fresh full query (and a
# stable-row subscription pushes a REAL delta), and the gateway's
# /metrics exposes the gyt_gw_* families.
echo "ci: query-fabric gateway smoke" >&2
if ! JAX_PLATFORMS=cpu python _gw_smoke.py; then
    echo "ci: FATAL — gateway smoke failed" >&2
    exit 1
fi

# Multichip smoke: a REAL `serve --shards 8` subprocess on the
# simulated 8-device mesh — per-shard ingest + WAL subdirs + collective
# roll-up; 2 agents on different shards; asserts the MERGED
# svcstate/topk rows are non-empty and byte-equal on REST and stock NM,
# chunks routed to their layout shards, per-shard gauges exposed.
echo "ci: multichip --shards smoke" >&2
if ! JAX_PLATFORMS=cpu python _multichip_smoke.py; then
    echo "ci: FATAL — multichip smoke failed" >&2
    exit 1
fi

# Multi-process ingest smoke: a REAL `serve --shards 8
# --ingest-procs 2` subprocess — registration + fd handoff to sticky
# shard-group workers, worker-side deframe/decode + WAL append,
# shared-memory rings into the fold; 2 agents on different shard
# groups; asserts merged svcstate byte-equal on REST and stock NM,
# per-worker heartbeat gauges + ledger counters in /metrics, and the
# worker-owned per-shard WAL in the stock layout.
echo "ci: multi-process ingest smoke" >&2
if ! JAX_PLATFORMS=cpu python _mproc_smoke.py; then
    echo "ci: FATAL — mproc smoke failed" >&2
    exit 1
fi

# Chaos smoke: a REAL `serve` subprocess behind the seeded chaos proxy
# (sim/chaos.py) — corruption/disconnect faults, a slow-loris conn,
# one SIGTERM kill + --restore-latest restart. Fails on agent exit,
# non-convergence, an unreaped loris, or unaccounted record loss.
echo "ci: chaos / fault-injection smoke" >&2
if ! JAX_PLATFORMS=cpu python _chaos_smoke.py; then
    echo "ci: FATAL — chaos smoke failed" >&2
    exit 1
fi

# Fabric fault-domain smoke (ISSUE 15): phase A — 2 replicas + 2 REAL
# gateway subprocesses with a wedge-capable chaos proxy (gateway
# SIGKILL mid-subscription → counted resync + byte-equal continuation
# on the peer, restart resumes from the persisted ring with a DELTA,
# wedged replica bounded by hedged reads, killed replica opens the
# circuit breaker — zero surfaced upstream errors throughout); phase
# B — `serve --shards 2 --ingest-procs 2` subprocess (fresh scoped
# XLA cache): ingest worker SIGKILL under subscription load with the
# ring ledger closing EXACTLY, and a compaction-worker death at a
# shard boundary failing loudly then converging on rerun.
echo "ci: fabric fault-domain smoke" >&2
if ! JAX_PLATFORMS=cpu python _fabric_chaos_smoke.py; then
    echo "ci: FATAL — fabric fault-domain smoke failed" >&2
    exit 1
fi

# Continuous-query smoke (ISSUE 18): 2 replicas + gateway, 104
# standing filters (96 hub + 8 real SSE) spelled 8 ways over 4
# canonical criteria groups on churning svcstate. Asserts the
# amortization contract off /metrics (gyt_cq_group_evals_total ==
# groups*ticks, gyt_cq_panel_renders_total == ticks — ≤1 render and
# one predicate pass per group per tick no matter how many
# subscribers), SSE-held membership byte-exact vs a brute-force
# predicate pass over the full panel, /v1/topology on REST + a stock
# NM conn, alertdef CQ evaluation byte-identical to degenerate per-def
# groups (fewer predicate passes, same fires/astate), the zero-def
# alert short-circuit counter, and enter/leave continuity across a
# gateway restart (persisted ring resumes with the missed deltas —
# counted as a resume, zero resyncs).
echo "ci: continuous-query smoke" >&2
if ! JAX_PLATFORMS=cpu python _cq_smoke.py; then
    echo "ci: FATAL — continuous-query smoke failed" >&2
    exit 1
fi

# Two-region WAN smoke (ISSUE 19): region A = hub Runtime + REAL
# gateway subprocess; region B = REAL `relay` + hub-mode `gateway`
# subprocesses with 3 agents, BOTH WAN hops through chaos proxies
# carrying asymmetric latency. Asserts: steady-state inter-region
# bytes ∝ delta churn (not panel size) with one WAN stream per key;
# relay-worker SIGKILL → respawn = a NEW counted epoch with the
# published == consumed + dropped ledger closing EXACTLY across TCP;
# full inter-region partition → bytes LOST (not parked) → heal
# resumes with a counted in-band resync/reconnect and byte-equal
# convergence; region-B wipeout (gateway + relay SIGKILL) → region A
# keeps serving, restarted region B converges byte-equal to the
# fault-free control. Never silent divergence.
echo "ci: two-region WAN smoke" >&2
if ! JAX_PLATFORMS=cpu python _region_smoke.py; then
    echo "ci: FATAL — two-region WAN smoke failed" >&2
    exit 1
fi

# Remote compaction region smoke (ISSUE 20): sealed WAL segments ship
# from a 2-shard source region over the supervised segship protocol to
# a compaction region's staging dir, under the full crash campaign —
# shipper SIGKILL at EVERY ship boundary (one death per landed
# segment, exit code enforced), receiver self-kill alternating between
# the post-rename and post-ledger crash points, and a WAN partition
# dropped mid-segment (stream hole → counted reconnect → per-segment
# offset resume). Asserts: the staging dir converges BYTE-IDENTICAL to
# the source WAL, the content-hash ledger closes EXACTLY
# (sealed == landed + counted drops, zero drops here), and a parallel
# replay of the SHIPPED staging dir through the serve daemon's staging
# loop is array-for-array identical to a local parallel replay of the
# original WAL. Never silent divergence.
echo "ci: remote compaction region smoke" >&2
if ! JAX_PLATFORMS=cpu python _rcompact_smoke.py; then
    echo "ci: FATAL — remote compaction smoke failed" >&2
    exit 1
fi

# Fused fold-path smoke: (a) the fused megakernel is the DEFAULT fold
# path (a regression to the legacy per-subsystem dispatch sequence
# would silently cost 2-6x fold throughput); (b) GYT_PALLAS=1 on a
# backend without a usable Pallas lowering falls back to the XLA
# scatter path cleanly — same folded state, no error on the hot path.
echo "ci: fused fold-path / pallas fallback smoke" >&2
if ! JAX_PLATFORMS=cpu python - <<'PYEOF'
import os
import subprocess
import sys

from gyeeta_tpu.runtime import fused_fold_enabled

assert fused_fold_enabled(env={}), "fused fold must be the default"
assert not fused_fold_enabled(env={"GYT_FUSED_FOLD": "0"})

# One leg per PROCESS: GYT_PALLAS is read at trace time and compiled
# fold variants are process-memoized, so an in-process env toggle
# would silently reuse the XLA-scatter executables.
LEG = r"""
import hashlib
import numpy as np
import jax
from gyeeta_tpu.runtime import Runtime
from gyeeta_tpu.sim.partha import ParthaSim
rt = Runtime()
assert rt._fused, "fused fold path not active by default"
sim = ParthaSim(n_hosts=4, n_svcs=4, seed=3)
rt.feed(sim.listener_frames())
rt.feed(sim.conn_frames(4096))
rt.feed(sim.resp_frames(4096))
rt.flush()
assert rt.stats.counters.get("fold_dispatches", 0) > 0
h = hashlib.sha256()
for x in jax.tree.leaves(rt.state):
    h.update(np.asarray(x).tobytes())
print("DIGEST", h.hexdigest())
rt.close()
"""

def leg(extra_env):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
    p = subprocess.run([sys.executable, "-c", LEG], env=env,
                       capture_output=True, text=True, timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    return [ln for ln in p.stdout.splitlines()
            if ln.startswith("DIGEST")][0]

base = leg({})
pall = leg({"GYT_PALLAS": "1"})  # interpret mode or clean XLA fallback
assert base == pall, "GYT_PALLAS path diverged from the XLA scatters"
print("ci: fused fold default + pallas fallback OK")
PYEOF
then
    echo "ci: FATAL — fused fold-path smoke failed" >&2
    exit 1
fi

if [ "$1" = "fast" ]; then
    shift
    exec python -m pytest tests/ -q -m "not slow" "$@"
fi
# Full runs compile shard_map mesh programs; RELOADING those from the
# persistent XLA cache segfaults on the 0.4.x jaxlib line (see
# tests/conftest.py). Clear the test-scoped cache so every full run is
# an all-miss (compile) run — slower, never crashing.
rm -rf "$HOME/.cache/gyeeta_tpu_jax/tests_"* 2>/dev/null || true
python -m pytest tests/ -q "$@"
