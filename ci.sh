#!/bin/sh
# CI entry: test suite on the 8-device virtual CPU platform.
# (tests/conftest.py forces JAX_PLATFORMS=cpu + the device count itself.)
#
#   ./ci.sh            full suite (slow: ~15 min on a 1-core box)
#   ./ci.sh fast       unit tier only (-m "not slow", a few minutes) —
#                      run this on every change; the full suite at least
#                      once before shipping
set -e
cd "$(dirname "$0")"
if [ "$1" = "fast" ]; then
    shift
    exec python -m pytest tests/ -q -m "not slow" "$@"
fi
python -m pytest tests/ -q "$@"
