#!/bin/sh
# CI entry: full test suite on the 8-device virtual CPU platform.
# (tests/conftest.py forces JAX_PLATFORMS=cpu + the device count itself.)
set -e
cd "$(dirname "$0")"
python -m pytest tests/ -q "$@"
