"""TPU ablation driver: run the fold bench with components removed."""
import os, subprocess, sys
combos = ["", "topk", "tdigest", "topk,tdigest", "upsert",
          "svchll", "globhll", "cms", "loghist", "ctr",
          "topk,tdigest,svchll,globhll,cms,loghist,ctr,upsert"]
for ab in combos:
    env = dict(os.environ, GYT_BENCH_ABLATE=ab, GYT_BENCH_NO_FEED="1")
    p = subprocess.run([sys.executable, "bench.py"], env=env,
                       capture_output=True, text=True, timeout=1800)
    ms = [l.split("]: ", 1)[-1] for l in p.stderr.splitlines()
          if "ms/dispatch" in l]
    print(f"{ab or 'FULL':44s} "
          f"{' | '.join(ms) if ms else p.stderr[-200:]}",
          flush=True)
