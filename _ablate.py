"""TPU ablation driver: run the fold bench with components removed.

Uses bench.py's phase-leaf mode (GYT_BENCH_PHASE) so only the device
fold cost is attributed — no feed-path phases.
"""
import os
import subprocess
import sys

combos = ["", "topk", "hh", "topk,hh", "tdigest", "topk,tdigest",
          "upsert",
          "svchll", "globhll", "cms", "loghist", "ctr",
          "topk,hh,tdigest,svchll,globhll,cms,loghist,ctr,upsert"]
for ab in combos:
    ms = []
    for phase in ("fold_ns", "fold_toy"):
        env = dict(os.environ, GYT_BENCH_ABLATE=ab,
                   GYT_BENCH_PHASE=phase)
        p = subprocess.run([sys.executable, "bench.py"], env=env,
                           capture_output=True, text=True, timeout=1800)
        ms += [ln.split("]: ", 1)[-1] for ln in p.stderr.splitlines()
               if "ms/dispatch" in ln]
        if p.returncode != 0 and not ms:
            ms.append(p.stderr[-150:].replace("\n", " "))
    print(f"{ab or 'FULL':44s} {' | '.join(ms)}", flush=True)
