"""CI smoke: the time-travel history tier end to end.

Feeds a runtime (journaling every accepted chunk), SEALS the WAL,
COMPACTS sealed segments into columnar snapshot shards (with a
retention geometry tight enough that the pass also DOWNSAMPLES raw →
mid — the retention sweep demonstrated live), RESTARTS (a fresh
process-equivalent Runtime over the same shard dir — no live engine
state survives), then queries ``svcstate?at=`` and ``topk?window=``
over BOTH the REST gateway and a stock NM conn, asserting non-empty,
bound-annotated, byte-equal rows. Exit code 0 = the history tier's
serving contract holds. Run by ci.sh; standalone:
``JAX_PLATFORMS=cpu python _hist_smoke.py``.
"""

from __future__ import annotations

import asyncio
import sys
import tempfile


async def _rest_query(gh, gp, req: dict) -> tuple:
    """POST /query against the web gateway → (raw body, parsed)."""
    import json

    reader, writer = await asyncio.open_connection(gh, gp)
    body = json.dumps(req).encode()
    writer.write(
        b"POST /query HTTP/1.1\r\nHost: s\r\nConnection: close\r\n"
        + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    raw = await reader.read(-1)
    writer.close()
    head, _, rbody = raw.partition(b"\r\n\r\n")
    assert b" 200 " in head.splitlines()[0], head
    return rbody, json.loads(rbody)


async def scenario(tmp: str) -> None:
    import json
    import os

    from gyeeta_tpu.engine.aggstate import EngineCfg
    from gyeeta_tpu.history.compactor import Compactor
    from gyeeta_tpu.history.shards import ShardStore
    from gyeeta_tpu.net import GytServer
    from gyeeta_tpu.net.webgw import WebGateway
    from gyeeta_tpu.runtime import Runtime
    from gyeeta_tpu.sim.nodeweb import NodeWebSim
    from gyeeta_tpu.sim.partha import ParthaSim
    from gyeeta_tpu.utils.config import RuntimeOpts

    cfg = EngineCfg(n_hosts=8, svc_capacity=64, task_capacity=64,
                    conn_batch=128, resp_batch=256, fold_k=2)
    opts = RuntimeOpts(
        journal_dir=os.path.join(tmp, "wal"),
        hist_shard_dir=os.path.join(tmp, "shards"),
        # 1-tick raw windows + tight retention so this very pass
        # exercises the raw→mid downsample sweep
        hist_window_ticks=1, hist_retain_raw=2, hist_mid_every=2,
        dep_pair_capacity=1024, dep_edge_capacity=512)

    # ---- phase 1: feed + tick (every accepted chunk lands in the WAL)
    rt = Runtime(cfg, opts)
    sim = ParthaSim(n_hosts=8, n_svcs=4, seed=5)
    rt.feed(sim.name_frames())
    for _ in range(6):
        rt.feed(sim.conn_frames(256) + sim.resp_frames(512)
                + sim.listener_frames() + sim.task_frames())
        rt.run_tick()
    final_tick = rt._tick_no

    # ---- phase 2: seal + compact (+ retention downsample)
    comp = Compactor(cfg, opts, journal=rt.journal, stats=rt.stats)
    rep = comp.compact_once(seal=True, upto_tick=final_tick)
    assert rep["windows"] == 6, rep
    assert rep["records"] > 0 and rep["ev_per_sec"] > 0, rep
    store = comp.store
    mids = store.shards("mid")
    raws = store.shards("raw")
    assert mids, "retention must have downsampled raw shards to mid"
    assert len(raws) <= opts.hist_retain_raw + opts.hist_mid_every
    assert rt.stats.counters["compact_shards"] >= 6
    assert rt.stats.counters["compact_downsampled"] >= 1
    named = {e["file"] for e in store.shards()}
    on_disk = {p.name for p in store.dir.glob("gyt_shard_*.npz")}
    assert named == on_disk, "manifest/file mismatch after retention"
    print(f"hist smoke: compacted {rep['windows']} windows "
          f"({rep['records']} records, {rep['ev_per_sec']:.0f} ev/s), "
          f"{len(raws)} raw + {len(mids)} mid shard(s)",
          file=sys.stderr)
    comp.close()
    rt.close()

    # ---- phase 3: RESTART — a fresh runtime over the same shard dir;
    # no live state, every answer must come from the shards
    rt2 = Runtime(cfg, opts)
    srv = GytServer(rt2, tick_interval=None)
    host, port = await srv.start()
    gw = WebGateway(host, port)
    gh, gp = await gw.start()
    nw = NodeWebSim(hostname="ci-hist")
    hs = await nw.connect(host, port)
    assert hs["error_code"] == 0, hs

    reqs = (
        {"subsys": "svcstate", "at": f"tick:{final_tick}",
         "maxrecs": 50},
        {"subsys": "topk", "window": "1h", "maxrecs": 50},
    )
    for req in reqs:
        nm_obj = await nw.request(
            2, {"qtype": req["subsys"],
                "options": {k: v for k, v in req.items()
                            if k != "subsys"}})
        rest_raw, rest_obj = await _rest_query(gh, gp, req)
        assert json.dumps(nm_obj).encode() == rest_raw, \
            f"NM vs REST bytes differ for {req}"
        assert nm_obj["nrecs"] > 0, (req, nm_obj)
    at_sv = await nw.request(2, {"qtype": "svcstate", "options": {
        "at": f"tick:{final_tick}", "maxrecs": 50}})
    assert at_sv["tick"] == final_tick
    win_tk = (await _rest_query(gh, gp, reqs[1]))[1]
    assert all("errbound" in r and "source" in r
               for r in win_tk["recs"]), win_tk["recs"][:3]
    # /metrics carries the compaction rows (written into the live
    # registry by the compactor pass above — scrape the NEW server's
    # exposition for the shard-store gauges at least)
    met = await nw.query_web("metrics")
    assert "gyt_stage_duration_seconds" in met["text"]
    print("hist smoke: at=/window= byte-equal on NM + REST, "
          f"{win_tk['nrecs']} bound-annotated topk row(s)",
          file=sys.stderr)

    await nw.close()
    await gw.stop()
    await srv.stop()
    rt2.close()
    store2 = ShardStore(opts.hist_shard_dir)
    assert store2.position() is not None


async def scenario_parallel(tmp: str) -> None:
    """ISSUE 14: distributed compaction end to end — a SHARDED WAL
    compacts with ``--compact-procs 2`` into a parted store, a fresh
    runtime RESTARTS over it, and a windowed p99 query (a TRUE merged
    quantile) serves non-empty byte-equal rows over the REST gateway
    AND a stock NM conn."""
    import json
    import os

    from gyeeta_tpu.engine.aggstate import EngineCfg
    from gyeeta_tpu.history.compactproc import ParallelCompactor
    from gyeeta_tpu.history.shards import PartedShardStore, \
        open_shard_store
    from gyeeta_tpu.net import GytServer
    from gyeeta_tpu.net.webgw import WebGateway
    from gyeeta_tpu.runtime import Runtime
    from gyeeta_tpu.sim.nodeweb import NodeWebSim
    from gyeeta_tpu.sim.partha import ParthaSim
    from gyeeta_tpu.utils import journal as J
    from gyeeta_tpu.utils.config import RuntimeOpts
    from gyeeta_tpu.utils.selfstats import Stats

    cfg = EngineCfg(n_hosts=8, svc_capacity=64, task_capacity=64,
                    conn_batch=128, resp_batch=256, fold_k=2)
    wal = os.path.join(tmp, "pwal")
    ticks = 4
    # sharded WAL, host-disjoint per shard (the serve --shards layout)
    for s in range(2):
        j = J.Journal(os.path.join(wal, f"shard_{s:02d}"))
        sim = ParthaSim(n_hosts=4, n_svcs=2, seed=50 + s,
                        host_base=s * 4)
        j.append(sim.name_frames(), hid=s * 4, tick=0)
        for t in range(ticks):
            j.append(sim.conn_frames(128) + sim.resp_frames(256)
                     + sim.listener_frames() + sim.task_frames(),
                     hid=s * 4, tick=t)
        j.close()

    opts = RuntimeOpts(hist_shard_dir=os.path.join(tmp, "pshards"),
                       hist_window_ticks=2,
                       dep_pair_capacity=1024, dep_edge_capacity=512)
    pc = ParallelCompactor(cfg, opts, 2, journal_dir=wal,
                           shard_dir=opts.hist_shard_dir,
                           stats=Stats())
    rep = pc.compact_once(upto_tick=ticks)
    pc.close()
    assert rep["workers"] == 2 and rep["windows"] == 4, rep
    assert isinstance(open_shard_store(opts.hist_shard_dir),
                      PartedShardStore)
    print(f"hist smoke: parallel compaction {rep['windows']} "
          f"window(s) across {rep['workers']} worker(s), "
          f"{rep['records']} records", file=sys.stderr)

    # RESTART over the parted store; windowed p99 on both edges
    rt = Runtime(cfg, opts)
    srv = GytServer(rt, tick_interval=None)
    host, port = await srv.start()
    gw = WebGateway(host, port)
    gh, gp = await gw.start()
    nw = NodeWebSim(hostname="ci-hist-par")
    hs = await nw.connect(host, port)
    assert hs["error_code"] == 0, hs

    req = {"subsys": "svcstate", "window": "1h",
           "columns": ["svcid", "p99resp5s", "p95resp5s", "resp5s"],
           "maxrecs": 50}
    nm_obj = await nw.request(
        2, {"qtype": "svcstate",
            "options": {k: v for k, v in req.items()
                        if k != "subsys"}})
    rest_raw, rest_obj = await _rest_query(gh, gp, req)
    assert json.dumps(nm_obj).encode() == rest_raw, \
        "NM vs REST bytes differ for the windowed-quantile query"
    assert nm_obj["nrecs"] > 0, nm_obj
    assert all("p99resp5s" in r and r["p99resp5s"] >= r["p95resp5s"]
               for r in nm_obj["recs"]), nm_obj["recs"][:3]
    at_req = {"subsys": "svcstate", "at": f"tick:{ticks}",
              "maxrecs": 50}
    at_obj = (await _rest_query(gh, gp, at_req))[1]
    assert at_obj["nrecs"] > 0 and at_obj["tick"] == ticks
    print(f"hist smoke: parted-store windowed p99 byte-equal on "
          f"NM + REST ({nm_obj['nrecs']} row(s))", file=sys.stderr)
    await nw.close()
    await gw.stop()
    await srv.stop()
    rt.close()


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="gyt_hist_smoke_") as tmp:
        asyncio.run(scenario(tmp))
    with tempfile.TemporaryDirectory(prefix="gyt_hist_smoke_") as tmp:
        asyncio.run(scenario_parallel(tmp))
    print("hist smoke: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
