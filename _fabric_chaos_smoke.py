"""CI smoke: fault-domain hardening of the distributed fabric (ISSUE 15).

Two phases over the inter-tier hops the PR-4 chaos tier never touched:

**Phase A — serving fabric** (2 replicas + 2 REAL gateway
subprocesses, one replica behind a wedge-capable chaos proxy):

- gateway SIGKILL mid-subscription → the supervised
  ``SubscribeStream`` hops to the peer gateway with ``last_snaptick``
  (the continuation gap is a COUNTED resync, never silent);
- the killed gateway RESTARTS over its ``--sub-persist`` ring and
  answers a reconnect inside the restored window with a DELTA;
- one replica WEDGED (stalled, not dead — the hard case): hedged
  reads bound query latency off the healthy replica;
- one replica KILLED: the circuit breaker marks it down after K real
  failures (flap counted, state visible in /metrics) and queries
  keep succeeding off the survivor;
- a strong-consistency query poller runs through EVERY fault window:
  zero queries surface an upstream error while >=1 replica is live,
  and p99 stays bounded;
- every subscriber's reassembled stream converges BYTE-EQUAL to an
  uninterrupted control subscription on the serve tier.

**Phase B — process tier under combined load** (a REAL ``serve
--shards 2 --ingest-procs 2`` subprocess, fresh scoped XLA cache, the
PR-12 subprocess methodology):

- ingest worker SIGKILL mid-feed (targeted from OUTSIDE via the new
  ``gyt_ingest_proc_pid`` gauge) while a subscription streams: the
  supervisor respawns it, the ring ledger closes EXACTLY
  (published == consumed + counted drops — zero silent record loss),
  and the subscriber's reassembled view matches a fresh query;
- compaction worker death at a shard boundary (the
  ``GYT_COMPACT_DIE_SHARD`` crash hook): the parallel pass fails
  LOUDLY, the parted store stays consistent, and a rerun converges.

Run by ci.sh; standalone: ``JAX_PLATFORMS=cpu python
_fabric_chaos_smoke.py [a|b]``.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


async def _until(cond, timeout=60.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        got = cond()
        if got:
            return got
        await asyncio.sleep(0.05)
    raise AssertionError(f"fabric smoke: timed out waiting for {msg}")


async def _http(port, method, path, body=b"", timeout=20.0):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        req = (f"{method} {path} HTTP/1.1\r\nHost: s\r\n"
               f"Connection: close\r\n"
               f"Content-Length: {len(body)}\r\n\r\n").encode() + body
        writer.write(req)
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), timeout)
    finally:
        writer.close()
    head, _, rbody = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), rbody


# ======================================================== phase A


def _spawn_gateway(listen_port, upstreams, peer_port, persist, tmp):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "gyeeta_tpu", "gateway",
           "--listen-port", str(listen_port),
           "--poll-s", "0.1", "--gw-down-after", "2",
           "--hedge-ms", "100", "--sub-persist", persist,
           "--advertise", f"127.0.0.1:{listen_port}",
           "--peer", f"127.0.0.1:{peer_port}"]
    for h, p in upstreams:
        cmd += ["--upstream", f"{h}:{p}"]
    return subprocess.Popen(cmd, cwd=HERE, env=env,
                            stderr=subprocess.DEVNULL)


async def phase_a(tmp: str) -> None:
    from gyeeta_tpu.engine.aggstate import EngineCfg
    from gyeeta_tpu.ingest import wire
    from gyeeta_tpu.net.server import GytServer
    from gyeeta_tpu.net.subs import SubscribeClient, SubscribeStream
    from gyeeta_tpu.query import delta as D
    from gyeeta_tpu.runtime import Runtime
    from gyeeta_tpu.sim.chaos import ChaosProxy, FaultPlan
    from gyeeta_tpu.sim.partha import ParthaSim

    cfg = EngineCfg(n_hosts=8, svc_capacity=256, task_capacity=256,
                    conn_batch=256, resp_batch=512, listener_batch=64,
                    fold_k=2)
    sim = ParthaSim(n_hosts=8, n_svcs=4, seed=15)

    def feed(rt):
        rt.feed(sim.conn_frames(256) + sim.resp_frames(512)
                + wire.encode_frame(wire.NOTIFY_HOST_STATE,
                                    sim.host_state_records()))

    # two replicas fed IDENTICALLY; replica 0 fronted by the chaos
    # proxy (wedge capability), replica 1 dialed directly
    replicas, servers = [], []
    for _ in range(2):
        rt = Runtime(cfg)
        rt.feed(sim.name_frames())
        rt.feed(sim.listener_frames())
        feed(rt)
        rt.run_tick()
        srv = GytServer(rt, tick_interval=None, idle_timeout=600.0)
        await srv.start()
        replicas.append(rt)
        servers.append(srv)
    proxy = ChaosProxy("127.0.0.1", servers[0].port, FaultPlan())
    ph, pp = await proxy.start()

    async def tick(only=None):
        for i, (rt, srv) in enumerate(zip(replicas, servers)):
            if only is not None and i != only:
                continue
            feed(rt)
            rt.run_tick()
        await servers[0].push_subscriptions()   # the control's hub

    gp1, gp2 = _free_port(), _free_port()
    persist1 = os.path.join(tmp, "gw1_subs.jsonl")
    persist2 = os.path.join(tmp, "gw2_subs.jsonl")
    ups = [("127.0.0.1", pp), ("127.0.0.1", servers[1].port)]
    gw1 = _spawn_gateway(gp1, ups, gp2, persist1, tmp)
    gw2 = _spawn_gateway(gp2, ups, gp1, persist2, tmp)

    async def healthy(port):
        try:
            st, body = await _http(port, "GET", "/healthz",
                                   timeout=5.0)
            return st == 200
        except OSError:
            return False

    async def wait_healthy(port, proc, msg):
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0:
            if proc.poll() is not None:
                raise AssertionError(f"{msg}: gateway exited rc="
                                     f"{proc.returncode}")
            if await healthy(port):
                return
            await asyncio.sleep(0.2)
        raise AssertionError(f"{msg}: never healthy")

    await wait_healthy(gp1, gw1, "gw1 boot")
    await wait_healthy(gp2, gw2, "gw2 boot")
    print("fabric smoke[a]: gateways up", file=sys.stderr)

    # ---- the query poller: strong-consistency (uncached → the real
    # failover/hedge path) through EVERY fault window. Contract:
    # zero upstream errors surface while >=1 replica is live; a DEAD
    # GATEWAY is the client's problem (it fails over to the peer).
    lat: list = []
    perrs: list = []
    pstop = asyncio.Event()

    async def poller():
        body = json.dumps({"subsys": "hoststate", "maxrecs": 8,
                           "consistency": "strong"}).encode()
        while not pstop.is_set():
            for port in (gp1, gp2):
                t0 = time.monotonic()
                try:
                    st, rb = await _http(port, "POST", "/query",
                                         body, timeout=15.0)
                except (OSError, asyncio.TimeoutError,
                        TimeoutError, ConnectionError):
                    continue            # dead/killed gateway: fail over
                if st == 200 and b'"error"' not in rb[:64]:
                    lat.append(time.monotonic() - t0)
                else:
                    perrs.append((port, st, rb[:160]))
                break
            await asyncio.sleep(0.1)

    ptask = asyncio.create_task(poller())

    # ---- control subscription: UNINTERRUPTED, direct on replica 0
    q = {"subsys": "svcstate", "sortcol": "qps5s", "sortdesc": True,
         "maxrecs": 50}
    ctl = SubscribeClient()
    await ctl.connect("127.0.0.1", servers[0].port)
    await ctl.subscribe(dict(q))
    control = {"held": None}

    async def ctl_loop():
        async for ev in ctl.events():
            control["held"] = D.apply_event(control["held"], ev)

    ctl_task = asyncio.create_task(ctl_loop())

    # ---- faulted subscriber: supervised stream over BOTH gateways
    stream = SubscribeStream([("127.0.0.1", gp1), ("127.0.0.1", gp2)],
                             q, stall_timeout=3.0, backoff_base=0.1)
    latest = {"held": None}

    async def stream_loop():
        async for held in stream.responses():
            latest["held"] = held

    stask = asyncio.create_task(stream_loop())

    # ---- a second subscription on gw1 whose ring will prove the
    # persisted continuation: hostlist rows are stable, so the
    # post-restart resume MUST be a delta
    q2 = {"subsys": "hostlist", "maxrecs": 64}
    sc2 = SubscribeClient()
    await sc2.connect("127.0.0.1", gp1)
    await sc2.subscribe(dict(q2))
    hl = {"held": None, "n": 0}

    async def hl_loop():
        try:
            async for ev in sc2.events():
                hl["held"] = D.apply_event(hl["held"], ev)
                hl["n"] += 1
        except (ConnectionError, OSError, RuntimeError):
            pass                        # gw1 dies below — expected

    hl_task = asyncio.create_task(hl_loop())

    await _until(lambda: latest["held"] and control["held"]
                 and hl["held"], msg="initial fulls")
    print("fabric smoke[a]: initial fulls received", file=sys.stderr)
    for _ in range(3):
        await tick()
        await asyncio.sleep(0.5)
    await _until(lambda: latest["held"]["snaptick"]
                 == control["held"]["snaptick"], timeout=30.0,
                 msg="pre-fault convergence")
    t_kill = hl["held"]["snaptick"]
    print(f"fabric smoke[a]: pre-fault converged at tick {t_kill}",
          file=sys.stderr)

    # ---- fault 1: gateway SIGKILL mid-subscription
    gw1.kill()
    gw1.wait(timeout=30)
    await tick()
    await asyncio.sleep(0.3)
    await tick()
    await _until(lambda: stream.counters["reconnects"] >= 1
                 and latest["held"]["snaptick"]
                 == control["held"]["snaptick"], timeout=45.0,
                 msg="stream continuation via gw2")
    assert json.dumps(latest["held"]) == json.dumps(control["held"]), \
        "faulted stream diverged from the control subscription"
    # the continuation gap was COUNTED, never silent (gw2 had no ring
    # for this key at the missed ticks)
    assert stream.counters.get("resyncs", 0) \
        + stream.counters.get("forced_resyncs", 0) >= 1, \
        dict(stream.counters)
    print(f"fabric smoke[a]: gateway SIGKILL OK — stream hopped to "
          f"gw2, byte-equal at tick {latest['held']['snaptick']}, "
          f"resyncs counted ({stream.counters.get('resyncs', 0)})",
          file=sys.stderr)

    # ---- fault 1b: the killed gateway RESTARTS over its persisted
    # ring and resumes an old subscriber with a DELTA (hostlist rows
    # are stable: a resync here would mean continuation failed)
    gw1 = _spawn_gateway(gp1, ups, gp2, persist1, tmp)
    await wait_healthy(gp1, gw1, "gw1 restart")
    st, mtext = await _http(gp1, "GET", "/metrics")
    assert st == 200
    assert b"gyt_gw_sub_persist_restored_keys" in mtext, \
        "restarted gateway did not restore the persisted ring"
    sc3 = SubscribeClient()
    await sc3.connect("127.0.0.1", gp1)
    await sc3.subscribe(dict(q2), last_snaptick=t_kill)
    agen = sc3.events(stall_timeout=30.0)
    ev = await agen.__anext__()
    assert ev["t"] == "delta" and ev["base"] == t_kill, (
        f"restarted gateway answered {ev.get('t')!r} "
        f"(base {ev.get('base')}) — expected a delta from the "
        f"persisted ring at {t_kill}")
    resumed = D.apply_event(hl["held"], ev)
    st, rb = await _http(gp1, "GET", "/v1/hostlist?maxrecs=64")
    fresh_hl = json.loads(rb)
    if fresh_hl["snaptick"] == resumed["snaptick"]:
        assert json.dumps(resumed) == json.dumps(fresh_hl)
    await sc3.close()
    print("fabric smoke[a]: restart continuation OK — persisted ring "
          f"replayed a delta from tick {t_kill}", file=sys.stderr)

    # ---- fault 2: replica 0 WEDGED (stalled, not dead). Hedged
    # reads bound latency off replica 1; nothing errors.
    proxy.wedged = True
    wedge_lat = []
    body = json.dumps({"subsys": "hoststate", "maxrecs": 8,
                       "consistency": "strong"}).encode()
    for _ in range(20):
        t0 = time.monotonic()
        st, rb = await _http(gp2, "POST", "/query", body, timeout=15.0)
        assert st == 200, rb[:200]
        wedge_lat.append(time.monotonic() - t0)
        await asyncio.sleep(0.05)
    proxy.wedged = False
    wedge_lat.sort()
    p99w = wedge_lat[int(0.99 * (len(wedge_lat) - 1))]
    assert p99w < 3.0, f"wedged-replica p99 {p99w:.2f}s unbounded"
    st, mtext = await _http(gp2, "GET", "/metrics")
    hedges = [ln for ln in mtext.decode().splitlines()
              if ln.startswith("gyt_gw_hedged_requests_total")]
    assert hedges and float(hedges[0].split()[-1]) >= 1, \
        "wedge phase fired no hedges"
    print(f"fabric smoke[a]: wedged replica OK — 20/20 strong "
          f"queries, p99 {p99w * 1e3:.0f}ms, "
          f"hedges {float(hedges[0].split()[-1]):.0f}",
          file=sys.stderr)

    # ---- fault 3: replica 1 KILLED outright. The breaker opens
    # after K real failures (flap counted, visible in /metrics);
    # queries keep succeeding off replica 0.
    await servers[1].stop()
    for _ in range(10):
        st, rb = await _http(gp2, "POST", "/query", body, timeout=15.0)
        assert st == 200, rb[:200]
        await asyncio.sleep(0.1)
    r1label = f"127.0.0.1:{servers[1].port}"

    async def breaker_open():
        st, mtext = await _http(gp2, "GET", "/metrics")
        t = mtext.decode()
        return (f'gyt_gw_upstream_state{{state="down",'
                f'upstream="{r1label}"}} 1' in t
                or f'gyt_gw_upstream_state{{upstream="{r1label}",'
                f'state="down"}} 1' in t)

    t0 = time.monotonic()
    while time.monotonic() - t0 < 30.0:
        if await breaker_open():
            break
        await asyncio.sleep(0.3)
    else:
        raise AssertionError("dead replica never marked down in "
                             "gw2 /metrics")
    st, mtext = await _http(gp2, "GET", "/metrics")
    assert b"gyt_gw_upstream_flaps_total" in mtext, \
        "no flap counter in /metrics"
    print("fabric smoke[a]: replica kill OK — circuit open + flap "
          "counted in /metrics, queries kept succeeding",
          file=sys.stderr)

    # ---- final convergence: feed replica 0 only, every stream
    # byte-equal to the control
    for _ in range(2):
        await tick(only=0)
        await asyncio.sleep(0.5)
    await _until(lambda: latest["held"]["snaptick"]
                 == control["held"]["snaptick"], timeout=45.0,
                 msg="final convergence")
    assert json.dumps(latest["held"]) == json.dumps(control["held"]), \
        "post-fault stream diverged from the control subscription"

    pstop.set()
    await asyncio.sleep(0.2)
    ptask.cancel()
    assert not perrs, (
        f"{len(perrs)} queries surfaced upstream errors with a live "
        f"replica: {perrs[:3]}")
    lat.sort()
    p99 = lat[int(0.99 * (len(lat) - 1))] if lat else 0.0
    assert len(lat) >= 50, f"poller only completed {len(lat)} queries"
    assert p99 < 3.0, f"campaign-wide query p99 {p99:.2f}s unbounded"
    print(f"fabric smoke[a]: OK — {len(lat)} polled queries, 0 "
          f"upstream errors, p99 {p99 * 1e3:.0f}ms, stream "
          f"counters {dict(stream.counters)}", file=sys.stderr)

    stream.stop()
    for t in (stask, ctl_task, hl_task):
        t.cancel()
    await ctl.close()
    await sc2.close()
    for p in (gw1, gw2):
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
    await proxy.stop()
    for srv in servers:
        if srv._server is not None:
            await srv.stop()


# ======================================================== phase B

N_SHARDS = 2
N_PROCS = 2


def _serve_env(tmp, cache="xla_serve"):
    return dict(
        os.environ, JAX_PLATFORMS="cpu", GYT_PLATFORM="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count="
                  f"{N_SHARDS}",
        JAX_COMPILATION_CACHE_DIR=os.path.join(tmp, cache),
        GYT_N_HOSTS="16", GYT_SVC_CAPACITY="256",
        GYT_TASK_CAPACITY="256", GYT_CONN_BATCH="256",
        GYT_RESP_BATCH="512", GYT_LISTENER_BATCH="64", GYT_FOLD_K="2",
        GYT_DEP_PAIR_CAPACITY="2048", GYT_DEP_EDGE_CAPACITY="1024")


def _metric_value(text: str, prefix: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(prefix) and not ln.startswith("# "):
            total += float(ln.split()[-1])
    return total


async def phase_b(tmp: str) -> None:
    from gyeeta_tpu.net.agent import NetAgent, QueryClient
    from gyeeta_tpu.net.subs import SubscribeStream

    port = _free_port()
    waldir = os.path.join(tmp, "wal")
    env = _serve_env(tmp)
    cmd = [sys.executable, "-m", "gyeeta_tpu", "serve",
           "--host", "127.0.0.1", "--port", str(port),
           "--shards", str(N_SHARDS), "--ingest-procs", str(N_PROCS),
           "--journal-dir", waldir,
           "--hostmap", os.path.join(tmp, "hostmap.json"),
           "--tick-interval", "0.5",
           "--handshake-timeout", "5", "--idle-timeout", "600",
           "--stats-interval", "60", "--log-level", "WARNING"]
    proc = subprocess.Popen(cmd, cwd=HERE, env=env)
    stop = asyncio.Event()
    tasks: list = []

    async def query(req, deadline_s=300.0):
        # fresh conn per call, retried against a DEADLINE: the
        # fresh-cache serve loop blocks for minutes at a stretch
        # while mesh programs compile on a contended 1-core box, so
        # individual requests time out without anything being wrong
        # — a shared conn would also desync after the first timeout
        last = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if proc.poll() is not None:
                raise AssertionError(
                    f"serve exited rc={proc.returncode}")
            c = QueryClient(connect_timeout=10.0,
                            request_timeout=120.0)
            try:
                await c.connect("127.0.0.1", port)
                return await c.query(dict(req))
            except Exception as e:      # noqa: BLE001 — retried
                last = e
                await asyncio.sleep(3.0)
            finally:
                await c.close()
        raise AssertionError(f"query {req} kept failing: {last}")

    try:
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"serve exited early rc={proc.returncode}")
            try:
                c = QueryClient(connect_timeout=2.0,
                                 request_timeout=30.0)
                await c.connect("127.0.0.1", port)
                await c.query({"subsys": "serverstatus"})
                await c.close()
                break
            except Exception:
                await asyncio.sleep(1.0)
        else:
            raise AssertionError("serve never became ready")

        # supervised agents on BOTH shard groups (sticky hids 0/1)
        agents = [NetAgent(machine_id=0x7B21 + i, seed=33 + i,
                           n_svcs=3, connect_timeout=420.0,
                           spool_max_bytes=1 << 20)
                  for i in range(2)]
        tasks = [asyncio.create_task(a.run_forever(
            "127.0.0.1", port, interval=0.5, n_conn=32, n_resp=32,
            backoff_base=0.2, backoff_cap=1.0, stop=stop))
            for a in agents]

        # the combined load: a SUPERVISED subscription through the
        # kill (reconnects across compile stalls with last_snaptick)
        stream = SubscribeStream(
            [("127.0.0.1", port)],
            {"subsys": "hoststate", "maxrecs": 16},
            stall_timeout=90.0, backoff_base=1.0)
        sub = {"held": None, "n": 0}

        async def sub_loop():
            async for held in stream.responses():
                sub["held"] = held
                sub["n"] += 1

        sub_task = asyncio.create_task(sub_loop())

        async def metrics_text():
            out = await query({"subsys": "metrics"})
            return out["text"]

        # wait until both hosts fold and the worker pid gauges are up
        async def pids():
            t = await metrics_text()
            out = {}
            for ln in t.splitlines():
                if ln.startswith("gyt_ingest_proc_pid{"):
                    w = ln.split('proc="')[1].split('"')[0]
                    out[w] = int(float(ln.split()[-1]))
            return out

        t0 = time.monotonic()
        while time.monotonic() - t0 < 300.0:
            hosts = await query({"subsys": "hoststate",
                                  "maxrecs": 16})
            if (hosts.get("nrecs", 0) >= 2
                    and len(await pids()) == N_PROCS
                    and sub["n"] >= 1):
                break
            await asyncio.sleep(1.0)
        else:
            raise AssertionError("phase b never reached steady state")

        # ---- SIGKILL one ingest worker mid-feed, targeted from
        # OUTSIDE via the pid gauge (the operator's path)
        p0 = await pids()
        victim = p0["0"]
        os.kill(victim, signal.SIGKILL)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 120.0:
            t = await metrics_text()
            cur = await pids()
            if (_metric_value(t, "gyt_ingest_proc_respawns_total"
                              '{proc="0"}') >= 1
                    and cur.get("0") and cur["0"] != victim):
                break
            await asyncio.sleep(1.0)
        else:
            raise AssertionError("worker never respawned after "
                                 "SIGKILL")
        await asyncio.sleep(4.0)        # reconnects + fresh sweeps

        # ---- the cross-process ledger closes EXACTLY (zero silent
        # record loss across the SIGKILL window). The supervisor
        # folds worker-counter deltas at ~1s cadence, so poll.
        stop.set()
        await asyncio.wait_for(asyncio.gather(*tasks), 30.0)
        tasks = []
        ledger = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0:
            t = await metrics_text()
            published = _metric_value(
                t, "gyt_ingest_proc_published_records_total")
            consumed = _metric_value(
                t, "gyt_ingest_ring_consumed_records_total")
            dropped = _metric_value(
                t, "gyt_ingest_ring_dropped_records")
            ledger = (published, consumed, dropped)
            if published > 0 and published == consumed + dropped:
                break
            await asyncio.sleep(1.0)
        else:
            raise AssertionError(
                f"ring ledger never closed: published={ledger[0]} "
                f"consumed={ledger[1]} dropped={ledger[2]}")

        # both hosts present after the kill; the subscriber's
        # reassembled view matches a fresh render at its tick
        hosts = await query({"subsys": "hoststate", "maxrecs": 16})
        assert hosts.get("nrecs", 0) >= 2, hosts
        ok = False
        for _ in range(20):
            fresh = await query({"subsys": "hoststate",
                                 "maxrecs": 16,
                                 "consistency": "snapshot"})
            if sub["held"] is not None and \
                    fresh.get("snaptick") == sub["held"].get(
                        "snaptick"):
                assert json.dumps(sub["held"]) == json.dumps(
                    json.loads(json.dumps(fresh)))
                ok = True
                break
            await asyncio.sleep(0.5)
        assert ok, "subscriber never aligned with a fresh render"
        stream.stop()
        sub_task.cancel()
        print(f"fabric smoke[b]: worker SIGKILL OK — respawned, "
              f"ledger exact (published={ledger[0]:.0f} == "
              f"consumed={ledger[1]:.0f} + dropped={ledger[2]:.0f}), "
              f"subscription byte-equal through the kill",
              file=sys.stderr)

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0, \
            f"serve shutdown rc={proc.returncode}"

        # ---- compaction worker death at a shard boundary: the
        # parallel pass fails LOUDLY, the store stays consistent, a
        # rerun converges (no --checkpoint-dir → the full WAL
        # survived the SIGTERM for offline compaction)
        shdir = os.path.join(tmp, "shards")
        base = [sys.executable, "-m", "gyeeta_tpu", "compact", "run",
                "--journal-dir", waldir, "--shard-dir", shdir,
                "--procs", str(N_PROCS), "--window-ticks", "4"]
        env_die = dict(_serve_env(tmp, cache="xla_c1"),
                       GYT_COMPACT_DIE_SHARD="1")
        r = subprocess.run(base, cwd=HERE, env=env_die,
                           capture_output=True, timeout=600)
        assert r.returncode != 0, \
            "compaction worker death did not fail the pass loudly"
        env_ok = _serve_env(tmp, cache="xla_c2")
        r2 = subprocess.run(base, cwd=HERE, env=env_ok,
                            capture_output=True, timeout=600)
        assert r2.returncode == 0, r2.stderr[-2000:]
        r3 = subprocess.run(
            [sys.executable, "-m", "gyeeta_tpu", "compact", "list",
             "--shard-dir", shdir], cwd=HERE, env=env_ok,
            capture_output=True, timeout=120)
        assert r3.returncode == 0, r3.stderr[-1000:]
        listing = json.loads(r3.stdout)
        assert listing.get("shards"), \
            f"no windows in the converged store: {listing}"
        print(f"fabric smoke[b]: compaction worker death OK — pass "
              f"failed loudly (rc={r.returncode}), rerun converged "
              f"({len(listing['shards'])} window(s))",
              file=sys.stderr)
    finally:
        stop.set()
        for t in tasks:
            t.cancel()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()


def main() -> int:
    which = sys.argv[1] if len(sys.argv) > 1 else "ab"
    tmp = tempfile.mkdtemp(prefix="gyt_fabric_smoke_")
    try:
        if "a" in which:
            os.makedirs(os.path.join(tmp, "a"), exist_ok=True)
            asyncio.run(phase_a(os.path.join(tmp, "a")))
        if "b" in which:
            os.makedirs(os.path.join(tmp, "b"), exist_ok=True)
            asyncio.run(phase_b(os.path.join(tmp, "b")))
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    print("fabric smoke: OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"fabric smoke: FAIL — {e}", file=sys.stderr)
        sys.exit(1)
